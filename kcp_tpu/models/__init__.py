from .reconcile_model import ReconcileDeltas, ReconcileModel, ReconcileState, reconcile_step

__all__ = ["ReconcileModel", "ReconcileState", "ReconcileDeltas", "reconcile_step"]
