"""The flagship device program: one fused reconcile step for the fleet.

This is the framework's "model": where the reference runs thousands of
goroutines each diffing one object (SURVEY.md §2.2), this program runs
the *entire control plane's* decision math as one compiled XLA step over
device-resident state:

  1. scatter the tick's informer deltas into the resident mirrors
  2. spec/status three-way diff over every row        (syncer lanes)
  3. replica placement over every root deployment      (splitter lane)
  4. label-selector fan-out over every object x cluster (informer lane)
  5. global convergence statistics (reduced across the mesh)

Everything is fixed-shape, branch-free, elementwise + masked-reduction
work: ideal VPU/HBM streaming with nothing blocking XLA fusion. The step
is donation-friendly (state in, state out) so steady-state runs entirely
in HBM; only the delta batch crosses the host<->device link each tick,
and only the decision lanes come back.

Sharding: see kcp_tpu/parallel/mesh.py — rows over the ``tenants`` axis,
slot columns optionally over ``slots``; the stats reductions become XLA
collectives. ``dryrun_multichip`` in __graft_entry__.py exercises exactly
this step over a multi-device mesh.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.diff import apply_deltas, compact_patches, sync_decisions
from ..ops.labelmatch import fanout_match
from ..ops.placement import placement_changed, split_replicas


class ReconcileState(NamedTuple):
    """Device-resident control-plane state (one schema bucket).

    B = object rows (all tenants), S = slot columns, R = root deployments,
    P = physical clusters, L = label slots, C = cluster selectors.
    """

    up_vals: jax.Array  # uint32 [B, S]
    up_exists: jax.Array  # bool [B]
    down_vals: jax.Array  # uint32 [B, S]
    down_exists: jax.Array  # bool [B]
    status_mask: jax.Array  # bool [S] (bucket-wide) or [B, S] (per-row)
    replicas: jax.Array  # int32 [R]
    avail: jax.Array  # bool [R, P]
    current: jax.Array  # int32 [R, P] currently-applied leaf replicas
    pair_hashes: jax.Array  # uint32 [B, L]
    sel_hashes: jax.Array  # uint32 [C]


class ReconcileDeltas(NamedTuple):
    """One tick's informer deltas, padded to a fixed D.

    Single-sided: a real informer event reports a change on exactly ONE
    side — the kcp (upstream/spec) stream or the physical (downstream/
    status) stream — the reference's two controllers each watch one
    apiserver (pkg/syncer/specsyncer.go:43-55, statussyncer.go:29-39).
    One payload column per row, routed by ``side``, halves the
    host->device bytes per tick vs. a both-sides layout.
    """

    idx: jax.Array  # int32 [D] row indices
    vals: jax.Array  # uint32 [D, S] new encoding (ignored for deletes)
    exists: jax.Array  # bool [D] False = delete event
    side: jax.Array  # bool [D] False = upstream mirror, True = downstream
    valid: jax.Array  # bool [D] padding mask


class ReconcileOutputs(NamedTuple):
    # compact lanes — the only thing the host applier fetches each tick
    patch_idx: jax.Array  # int32 [K] actionable row indices (pad = B)
    patch_code: jax.Array  # uint8 [K] decision per patch row
    patch_upsync: jax.Array  # bool [K] status-upsync flag per patch row
    patch_count: jax.Array  # int32 [] valid patch rows
    patch_overflow: jax.Array  # bool [] > K rows actionable this tick
    stats: jax.Array  # int32 [8] global counters (see STATS_FIELDS)
    # full lanes — stay device-resident; fetched only on patch_overflow
    # or by tests/debugging
    decision: jax.Array  # uint8 [B] NOOP/CREATE/UPDATE/DELETE
    status_upsync: jax.Array  # bool [B]
    leaf_replicas: jax.Array  # int32 [R, P] desired placement
    placement_dirty: jax.Array  # bool [R]
    match_counts: jax.Array  # int32 [C] objects matched per cluster selector


STATS_FIELDS = (
    "rows", "creates", "updates", "deletes", "upsyncs",
    "placement_dirty", "matched", "applied_deltas",
)


def reconcile_step(state: ReconcileState, deltas: ReconcileDeltas,
                   patch_capacity: int = 8192, use_pallas: bool = False,
                   mesh=None,
                   ) -> tuple[ReconcileState, ReconcileOutputs]:
    # 1. scatter deltas, routed by side (ops/diff.apply_deltas owns the
    #    padding-drop and dedup-by-key contract: delta batches must carry
    #    unique indices)
    up_vals, up_exists = apply_deltas(
        state.up_vals, state.up_exists, deltas.idx,
        deltas.vals, deltas.exists, deltas.valid & ~deltas.side,
    )
    down_vals, down_exists = apply_deltas(
        state.down_vals, state.down_exists, deltas.idx,
        deltas.vals, deltas.exists, deltas.valid & deltas.side,
    )

    b = up_vals.shape[0]
    local_b = b
    if use_pallas and mesh is not None:
        from ..parallel.mesh import row_factor, slot_factor

        # the kernel runs per row-shard and needs full S per row: fall
        # back to the (slot-partitioned) XLA lanes when b does not split
        # exactly into 128-multiples per shard, or when the slots axis
        # would force redundant all-gathered work on every slot shard
        if b % (128 * row_factor(mesh)) == 0 and slot_factor(mesh) == 1:
            local_b = b // row_factor(mesh)
        else:
            local_b = 1  # fails the gate below -> XLA lanes
    if use_pallas and local_b % 128 == 0:
        # 2+4 fused: one Pallas pass reads each row block into VMEM once
        # and emits the decision lanes + per-selector match counts
        # (ops/pallas_kernels.py; differential-tested vs the XLA lanes).
        # On a mesh the kernel runs per device on its local row block via
        # shard_map (counts psum across the row axes). block_rows must
        # DIVIDE the local rows AND fit the measured scoped-VMEM budget
        # for this slot width (max_block_rows; 128 always divides given
        # the gate, but a very wide bucket can fail the VMEM cap)
        from ..ops.pallas_kernels import (
            decide_and_match,
            decide_and_match_sharded,
            max_block_rows,
        )

        br = max_block_rows(local_b, up_vals.shape[1],
                            labels=state.pair_hashes.shape[1],
                            per_row_mask=state.status_mask.ndim == 2)
    else:
        br = 0
    if use_pallas and br:
        if mesh is not None:
            decision, status_upsync, match_counts = decide_and_match_sharded(
                mesh, up_vals, up_exists, down_vals, down_exists,
                state.status_mask, state.pair_hashes, state.sel_hashes,
                block_rows=br,
            )
        else:
            decision, status_upsync, match_counts = decide_and_match(
                up_vals, up_exists, down_vals, down_exists, state.status_mask,
                state.pair_hashes, state.sel_hashes, block_rows=br,
            )
        matched_total = match_counts.sum(dtype=jnp.int32)
    else:
        # 2. syncer lanes
        d = sync_decisions(up_vals, up_exists, down_vals, down_exists,
                           state.status_mask)
        decision, status_upsync = d.decision, d.status_upsync

        # 4. informer fan-out lane — only resident upstream objects fan
        #    out (pair_hashes rows of deleted objects are stale, not
        #    cleared)
        match = fanout_match(state.pair_hashes, state.sel_hashes) & up_exists[:, None]  # [B, C]
        match_counts = match.sum(axis=0, dtype=jnp.int32)
        matched_total = match.sum(dtype=jnp.int32)

    # 3. splitter lane
    leaf = split_replicas(state.replicas, state.avail)
    p_dirty = placement_changed(state.current, leaf)

    # 5. global stats — under a sharded mesh these reductions lower to
    #    XLA collectives over the tenants/slots axes
    stats = jnp.stack([
        up_exists.sum(dtype=jnp.int32),
        (decision == 1).sum(dtype=jnp.int32),
        (decision == 2).sum(dtype=jnp.int32),
        (decision == 3).sum(dtype=jnp.int32),
        status_upsync.sum(dtype=jnp.int32),
        p_dirty.sum(dtype=jnp.int32),
        matched_total,
        deltas.valid.sum(dtype=jnp.int32),
    ])

    new_state = ReconcileState(
        up_vals=up_vals, up_exists=up_exists,
        down_vals=down_vals, down_exists=down_exists,
        status_mask=state.status_mask,
        replicas=state.replicas, avail=state.avail, current=leaf,
        pair_hashes=state.pair_hashes, sel_hashes=state.sel_hashes,
    )
    patches = compact_patches(decision, status_upsync, patch_capacity)
    outputs = ReconcileOutputs(
        patch_idx=patches.idx, patch_code=patches.code,
        patch_upsync=patches.upsync, patch_count=patches.count,
        patch_overflow=patches.overflow,
        decision=decision, status_upsync=status_upsync,
        leaf_replicas=leaf, placement_dirty=p_dirty,
        match_counts=match_counts, stats=stats,
    )
    return new_state, outputs


reconcile_step_jit = jax.jit(
    reconcile_step, donate_argnums=(0,),
    static_argnames=("patch_capacity", "use_pallas", "mesh"),
)


# ---------------------------------------------------------------------------
# Packed wire format — one array per direction across the host<->device link.
#
# When the device sits behind a network tunnel (or another host, §2.3's
# "gRPC link ships informer deltas to a JAX worker which returns patch
# sets"), every array is its own transfer RPC; packing the tick's deltas
# into ONE uint32 array and the patch set + stats into ONE int32 array
# makes a tick exactly one upload and one download regardless of lane
# count. Patch entries carry row index (20 bits), decision code (2 bits,
# bit 20-21) and the status-upsync flag (bit 23).
#
# Wire layout (int32):
#   [0]                 patch count
#   [1]                 patch overflow flag
#   [2:10]              stats
#   [10]                placement-dirty count
#   [PACK_HDR : +K]     packed patch entries (K = patch_capacity)
#   [PACK_HDR+K : +R*(1+P)]  placement entries: R rows of
#                       (root row index or R for padding, P leaf counts)
#                       — dirty roots compacted first (the splitter lane
#                       rides the same wire as the sync lanes)
# ---------------------------------------------------------------------------

PACK_HDR = 16  # int32 slots ahead of the packed patch entries
PACK_IDX_MASK = (1 << 20) - 1
PACK_CODE_SHIFT = 20
PACK_UPSYNC_BIT = 1 << 23
PACK_PLACEMENT_COUNT = 10  # hdr slot carrying the placement-dirty count


def pack_deltas(deltas: ReconcileDeltas) -> np.ndarray:
    """Host-side: pack a delta batch into one uint32 [D, S+2] array."""
    d = np.asarray(deltas.vals).shape[0]
    flags = (
        np.asarray(deltas.exists).astype(np.uint32)
        | (np.asarray(deltas.side).astype(np.uint32) << 1)
        | (np.asarray(deltas.valid).astype(np.uint32) << 2)
    )
    return np.concatenate(
        [
            np.asarray(deltas.vals),
            np.asarray(deltas.idx).astype(np.uint32).reshape(d, 1),
            flags.reshape(d, 1),
        ],
        axis=1,
    )


MASK_STAMP_BIT = 8  # flag: entry carries a status-mask row, not a delta


def unpack_deltas(packed: jax.Array) -> ReconcileDeltas:
    """Device-side (inside jit): unpack the uint32 [D, S+2] wire array.

    Mask-stamp entries (flag bit 8) are not deltas — they are excluded
    from ``valid`` here and consumed by :func:`apply_mask_stamps`."""
    s = packed.shape[1] - 2
    flags = packed[:, s + 1]
    return ReconcileDeltas(
        idx=packed[:, s].astype(jnp.int32),
        vals=packed[:, :s],
        exists=(flags & 1) != 0,
        side=(flags & 2) != 0,
        valid=((flags & 4) != 0) & ((flags & MASK_STAMP_BIT) == 0),
    )


def apply_mask_stamps(status_mask: jax.Array, packed: jax.Array) -> jax.Array:
    """Scatter mask-stamp entries into the per-row status mask.

    A row allocated AFTER its bucket's last full upload has a host-side
    mask stamp (Section.row_for) that the device never saw — the delta
    wire carries values only. Without this lane the device's mask for
    such a row stays all-False, its status churn misreads as spec churn
    (UPDATE instead of upsync), the applier correctly no-ops the
    phantom UPDATE, and the object never converges — found by the
    randomized differential fuzz. Stamps ride the same packed array:
    flag bit 8, vals columns = the bool mask row.
    """
    if status_mask.ndim != 2:
        return status_mask  # bucket-wide [S] masks have no per-row lane
    b = status_mask.shape[0]
    s = packed.shape[1] - 2
    flags = packed[:, s + 1]
    sel = ((flags & 4) != 0) & ((flags & MASK_STAMP_BIT) != 0)
    idx = packed[:, s].astype(jnp.int32)
    tgt = jnp.where(sel, idx, b)  # non-stamp entries route OOB -> drop
    return status_mask.at[tgt].set(packed[:, :s] != 0, mode="drop")


def reconcile_step_packed(state: ReconcileState, packed: jax.Array,
                          acks: jax.Array | None = None,
                          patch_capacity: int = 8192, use_pallas: bool = False,
                          mesh=None,
                          ) -> tuple[ReconcileState, jax.Array]:
    """The wire-format step: one uint32 array in, one int32 array out.

    ``acks`` is the converged-row compression lane: int32 row indices
    (negative = padding) whose downstream mirror becomes a copy of the
    resident upstream mirror. A feedback event whose encoded row equals
    the up mirror the device already holds — the applier's up->down copy
    observed back through the downstream informer — needs only these 4
    bytes on the wire instead of a full (S+2)-column entry. The host
    stager proves eligibility (values equal the host up mirror AND no
    up-side entry staged this tick, so the resident row it copies is
    exactly that value); the copy runs before the delta scatter, which
    by the eligibility rule cannot touch an acked row's up side.

    Output layout: [0]=patch count, [1]=overflow flag, [2:10]=stats,
    [PACK_HDR:]=packed patch entries (see module comment).
    """
    if state.up_vals.shape[0] > PACK_IDX_MASK + 1:
        # row indices go up to B-1, so B == 2^20 exactly fits the field
        raise ValueError(
            f"packed patch entries hold 20-bit row indices; "
            f"B={state.up_vals.shape[0]} exceeds {PACK_IDX_MASK + 1} — "
            f"shard the bucket or use the unpacked ReconcileOutputs lanes"
        )
    if acks is not None and state.up_vals.shape[0] > 0:
        b = state.up_vals.shape[0]
        valid = (acks >= 0) & (acks < b)
        # padding (-1) must not scatter AT ALL: clipping it to a real row
        # would race that row's genuine ack (duplicate-index scatter order
        # is unspecified) — route padding out of bounds and drop it
        idx = jnp.where(valid, acks, b)
        gather = jnp.clip(acks, 0, b - 1)
        down_vals = state.down_vals.at[idx].set(
            state.up_vals[gather], mode="drop")
        down_exists = state.down_exists.at[idx].set(
            state.up_exists[gather], mode="drop")
        state = state._replace(down_vals=down_vals, down_exists=down_exists)
    state = state._replace(
        status_mask=apply_mask_stamps(state.status_mask, packed))
    new_state, out = reconcile_step(state, unpack_deltas(packed), patch_capacity,
                                    use_pallas=use_pallas, mesh=mesh)
    entries = (
        out.patch_idx
        | (out.patch_code.astype(jnp.int32) << PACK_CODE_SHIFT)
        | jnp.where(out.patch_upsync, PACK_UPSYNC_BIT, 0)
    )
    # placement segment: dirty roots compacted first, each carrying its
    # P leaf counts (the deployment splitter's serving lane)
    r = state.replicas.shape[0]
    dirty = out.placement_dirty
    (pidx,) = jnp.nonzero(dirty, size=r, fill_value=r)
    safe = jnp.minimum(pidx, r - 1)
    valid = pidx < r
    counts = jnp.where(valid[:, None], out.leaf_replicas[safe], 0)
    pl_entries = jnp.concatenate(
        [pidx.astype(jnp.int32)[:, None], counts.astype(jnp.int32)], axis=1
    ).reshape(-1)
    hdr = jnp.zeros(PACK_HDR, jnp.int32)
    hdr = hdr.at[0].set(out.patch_count)
    hdr = hdr.at[1].set(out.patch_overflow.astype(jnp.int32))
    hdr = hdr.at[2:10].set(out.stats)
    hdr = hdr.at[PACK_PLACEMENT_COUNT].set(dirty.sum(dtype=jnp.int32))
    return new_state, jnp.concatenate([hdr, entries, pl_entries])


# ---------------------------------------------------------------------------
# Fleet lane — cross-bucket ragged batching (syncer/core.py FleetBatch).
#
# The fleet batch packs every schema bucket's rows into ONE ReconcileState
# (rows range-partitioned by bucket, slot columns zero-padded to the widest
# bucket) so a tick is one pipelined device program for the whole tenant
# fleet. Each row carries a *segment id* — the owning section (engine) —
# resident on device as an int32 [B] lane beside the state. Two uses:
#
# - segment stamps: a row allocated after the last full upload ships its
#   segment id inside its MASK_STAMP wire entry (flag bits 8..23), the
#   same entry that carries its status mask — no extra wire entries;
# - per-segment counters: the step ends with a segment-sum of the new
#   ``up_exists`` lane, shipped on the wire tail, so admission usage
#   accounting (admission/quota.py) rides the same batch instead of a
#   host-side recount pass.
# ---------------------------------------------------------------------------

SEG_SHIFT = 8  # mask-stamp flag bits [8..23] carry the row's segment id
SEG_FIELD_MASK = 0xFFFF
# unowned/freed rows: always >= any real segment capacity, so the
# counter scatter drops them (capacities stay far below 16 bits)
SEG_NONE = 0xFFFF


def apply_seg_stamps(seg_ids: jax.Array, packed: jax.Array) -> jax.Array:
    """Scatter segment-id stamps from MASK_STAMP entries into the
    resident row->segment lane (the fleet analog of apply_mask_stamps:
    rows allocated after the last full upload are otherwise unknown to
    the device-side per-segment counters)."""
    b = seg_ids.shape[0]
    s = packed.shape[1] - 2
    flags = packed[:, s + 1]
    sel = ((flags & 4) != 0) & ((flags & MASK_STAMP_BIT) != 0)
    idx = packed[:, s].astype(jnp.int32)
    tgt = jnp.where(sel, idx, b)  # non-stamp entries route OOB -> drop
    seg = ((flags >> SEG_SHIFT) & SEG_FIELD_MASK).astype(jnp.int32)
    return seg_ids.at[tgt].set(seg, mode="drop")


def reconcile_step_fleet(state: ReconcileState, seg_ids: jax.Array,
                         packed: jax.Array, acks: jax.Array | None = None,
                         patch_capacity: int = 8192, seg_capacity: int = 8,
                         use_pallas: bool = False, mesh=None,
                         ) -> tuple[ReconcileState, jax.Array, jax.Array]:
    """The fleet-batch step: :func:`reconcile_step_packed` plus the
    resident segment lane and per-segment live-row counters.

    ``seg_ids`` (int32 [B], device-resident like the state) maps each
    fleet row to its owning section's segment id (SEG_NONE = unowned).
    The wire grows a tail of ``seg_capacity`` int32 counts — the number
    of live upstream rows per segment after this tick's scatter — which
    the host routes to the admission quota ledger. Out-of-range segment
    ids (padding, unowned rows) drop out of the scatter-add.
    """
    seg_ids = apply_seg_stamps(seg_ids, packed)
    new_state, wire = reconcile_step_packed(
        state, packed, acks, patch_capacity, use_pallas=use_pallas, mesh=mesh)
    counts = jnp.zeros(seg_capacity, jnp.int32).at[seg_ids].add(
        new_state.up_exists.astype(jnp.int32), mode="drop")
    return new_state, seg_ids, jnp.concatenate([wire, counts])


def unpack_seg_counts(wire: np.ndarray, patch_capacity: int, r: int, p: int,
                      seg_capacity: int) -> np.ndarray:
    """Host-side: the per-segment live-row counts from a fleet wire (the
    caller knows the submitted patch capacity, placement shape and
    segment capacity — FleetBatch snapshots them per submit)."""
    off = PACK_HDR + patch_capacity + r * (1 + p)
    return wire[off:off + seg_capacity]


class WireBuffers:
    """Double-buffered host staging for the packed-delta wire.

    The staging/donation contract of :func:`reconcile_step_packed`: the
    resident state is donated every tick, but the packed array is NOT —
    ``jax.device_put`` may still be reading the host buffer after it
    returns (async dispatch). A single reused staging array would let
    tick N+1's host-side packing scribble over tick N's in-flight
    transfer; fresh ``np.zeros`` per tick is safe but pays an allocation
    + page-fault cost on every tick of the hot loop. Two rotating
    buffers make reuse safe at pipeline depth 2: ``acquire`` hands out
    the least-recently-used (packed, acks) pair, first blocking — only
    if the pipeline ran ahead of the transfer engine — until the device
    arrays that last consumed that pair are ready.
    """

    def __init__(self, depth: int = 2):
        self.depth = depth
        self._packed: list[np.ndarray | None] = [None] * depth
        self._acks: list[np.ndarray | None] = [None] * depth
        # device arrays whose transfer last read each slot's host buffers
        self._pending: list[tuple | None] = [None] * depth
        self._i = 0
        self.reuse_waits = 0  # acquires that had to block on a transfer

    def acquire(self, d: int, width: int,
                ack_capacity: int) -> tuple[int, np.ndarray, np.ndarray]:
        """A zeroed ``uint32 [d, width]`` packed buffer plus a -1-filled
        ``int32 [ack_capacity]`` acks buffer, safe to fill immediately.
        Returns ``(slot, packed, acks)``; pass ``slot`` to :meth:`commit`
        with the device arrays produced from these buffers."""
        i = self._i
        self._i = (i + 1) % self.depth
        pending = self._pending[i]
        if pending is not None:
            self._pending[i] = None
            for arr in pending:
                if not arr.is_ready():
                    self.reuse_waits += 1
                    arr.block_until_ready()
        packed = self._packed[i]
        if packed is None or packed.shape != (d, width):
            packed = self._packed[i] = np.zeros((d, width), np.uint32)
        else:
            packed.fill(0)
        acks = self._acks[i]
        if acks is None or acks.shape != (ack_capacity,):
            acks = self._acks[i] = np.full(ack_capacity, -1, np.int32)
        else:
            acks.fill(-1)
        return i, packed, acks

    def commit(self, slot: int, *device_arrays) -> None:
        """Record the device arrays whose host->device transfer reads the
        slot's buffers; the next acquire of this slot gates on them."""
        self._pending[slot] = device_arrays


def unpack_patches(wire: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray, bool, np.ndarray]:
    """Host-side: (idx, code, upsync, overflow, stats) from the wire array."""
    count = int(wire[0])
    entries = wire[PACK_HDR:PACK_HDR + count]
    return (
        entries & PACK_IDX_MASK,
        (entries >> PACK_CODE_SHIFT) & 3,
        (entries & PACK_UPSYNC_BIT) != 0,
        bool(wire[1]),
        wire[2:10],
    )


def unpack_placement(wire: np.ndarray, patch_capacity: int, p: int,
                     r: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: (dirty root row indices [N], leaf counts [N, P]) from
    the wire's placement segment (the caller knows the bucket's static
    patch_capacity and cluster width P). ``r`` bounds the segment to
    ``r`` placement rows — required for fleet wires, whose tail carries
    the per-segment counters after the placement entries."""
    n = int(wire[PACK_PLACEMENT_COUNT])
    seg = wire[PACK_HDR + patch_capacity:]
    if r is not None:
        seg = seg[:r * (1 + p)]
    seg = seg.reshape(-1, 1 + p)
    return seg[:n, 0], seg[:n, 1:]


def example_state(
    b: int = 8192, s: int = 64, r: int = 1024, p: int = 8, l: int = 8, c: int = 64,
    seed: int = 0, dirty_frac: float = 0.01,
) -> ReconcileState:
    """A synthetic populated state (host numpy; device placement is the
    caller's choice so meshes can shard it)."""
    rng = np.random.default_rng(seed)
    up = rng.integers(1, 2**32, size=(b, s), dtype=np.uint32)
    down = up.copy()
    flip = rng.random(b) < dirty_frac
    down[flip, :1] ^= 1
    status_mask = np.zeros(s, bool)
    status_mask[-max(1, s // 8):] = True
    return ReconcileState(
        up_vals=up,
        up_exists=np.ones(b, bool),
        down_vals=down,
        down_exists=np.ones(b, bool),
        status_mask=status_mask,
        replicas=rng.integers(0, 100, size=r).astype(np.int32),
        avail=rng.random((r, p)) < 0.9,
        current=np.zeros((r, p), np.int32),
        pair_hashes=rng.integers(1, 2**32, size=(b, l), dtype=np.uint32),
        sel_hashes=rng.integers(1, 2**32, size=c, dtype=np.uint32),
    )


def example_deltas(b: int = 8192, s: int = 64, d: int = 256, seed: int = 1) -> ReconcileDeltas:
    rng = np.random.default_rng(seed)
    # unique indices: the apply_deltas contract (duplicate in-batch scatter
    # order is unspecified; the host batcher deduplicates by key)
    return ReconcileDeltas(
        idx=rng.permutation(b)[:d].astype(np.int32),
        vals=rng.integers(1, 2**32, size=(d, s), dtype=np.uint32),
        exists=np.ones(d, bool),
        side=rng.random(d) < 0.5,
        valid=rng.random(d) < 0.9,
    )


class ReconcileModel:
    """Convenience wrapper holding compiled step + device state."""

    def __init__(self, state: ReconcileState, mesh=None, donate: bool = True):
        if mesh is not None:
            from ..parallel.mesh import shard_state

            state = shard_state(state, mesh)
        else:
            state = jax.tree.map(jax.device_put, state)
        self.state = state
        self._step = reconcile_step_jit if donate else jax.jit(reconcile_step)

    def step(self, deltas: ReconcileDeltas) -> ReconcileOutputs:
        self.state, out = self._step(self.state, deltas)
        return out
