"""Loader + ctypes bindings for the native runtime library.

The C++ library (``native/`` at the repo root) provides the runtime
components the reference keeps native-adjacent (its embedded etcd is a
Go-wrapped C-lineage storage engine; pkg/etcd/etcd.go): a durable WAL
storage engine and the object-encoding hot loop. Python is the
orchestration layer; anything that runs per-mutation or per-object goes
through here when the library is available.

The library is built on demand with ``make`` (toolchain is expected in
the image); if building or loading fails, ``load()`` returns ``None``
and every caller falls back to the pure-Python path — the native layer
is an accelerator, never a requirement. Set ``KCP_TPU_NO_NATIVE=1`` to
force the fallback (used by differential tests).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator

from ..analysis.sanitize import make_lock

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_NAME = "libkcpnative.so"

_lock = make_lock("native.load")
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _sources_newer_than_lib(lib_path: str) -> bool:
    lib_mtime = os.path.getmtime(lib_path)
    for fn in os.listdir(_NATIVE_DIR):
        if fn.endswith((".cc", ".h")) and os.path.getmtime(os.path.join(_NATIVE_DIR, fn)) > lib_mtime:
            return True
    return False


def _declare(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    lib.ws_open.restype = ctypes.c_void_p
    lib.ws_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.ws_close.argtypes = [ctypes.c_void_p]
    lib.ws_last_error.restype = ctypes.c_char_p
    lib.ws_last_error.argtypes = [ctypes.c_void_p]
    lib.ws_put.restype = ctypes.c_int
    lib.ws_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                           ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
    lib.ws_del.restype = ctypes.c_int
    lib.ws_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
    lib.ws_get.restype = ctypes.c_int
    lib.ws_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                           ctypes.POINTER(u8p), u32p]
    lib.ws_rv.restype = ctypes.c_uint64
    lib.ws_rv.argtypes = [ctypes.c_void_p]
    lib.ws_count.restype = ctypes.c_uint64
    lib.ws_count.argtypes = [ctypes.c_void_p]
    lib.ws_flush.restype = ctypes.c_int
    lib.ws_flush.argtypes = [ctypes.c_void_p]
    lib.ws_batch_begin.restype = ctypes.c_int
    lib.ws_batch_begin.argtypes = [ctypes.c_void_p]
    lib.ws_batch_commit.restype = ctypes.c_int
    lib.ws_batch_commit.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ws_batch_abort.restype = ctypes.c_int
    lib.ws_batch_abort.argtypes = [ctypes.c_void_p]
    lib.ws_epoch.restype = ctypes.c_uint64
    lib.ws_epoch.argtypes = [ctypes.c_void_p]
    lib.ws_set_epoch.restype = ctypes.c_int
    lib.ws_set_epoch.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ws_set_rv.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ws_snapshot.restype = ctypes.c_int
    lib.ws_snapshot.argtypes = [ctypes.c_void_p]
    lib.ws_snapshot_begin.restype = ctypes.c_int
    lib.ws_snapshot_begin.argtypes = [ctypes.c_void_p]
    lib.ws_snapshot_add.restype = ctypes.c_int
    lib.ws_snapshot_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                                    ctypes.c_char_p, ctypes.c_uint32]
    lib.ws_snapshot_commit.restype = ctypes.c_int
    lib.ws_snapshot_commit.argtypes = [ctypes.c_void_p]
    lib.ws_index_release.argtypes = [ctypes.c_void_p]
    lib.ws_scan.restype = ctypes.c_void_p
    lib.ws_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.ws_scan_next.restype = ctypes.c_int
    lib.ws_scan_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(u8p), u32p,
                                 ctypes.POINTER(u8p), u32p]
    lib.ws_scan_free.argtypes = [ctypes.c_void_p]

    lib.enc_bucket_new.restype = ctypes.c_void_p
    lib.enc_bucket_new.argtypes = [ctypes.c_uint32]
    lib.enc_bucket_free.argtypes = [ctypes.c_void_p]
    lib.enc_bucket_encode.restype = ctypes.c_int
    lib.enc_bucket_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, u32p]
    lib.enc_bucket_nslots.restype = ctypes.c_uint32
    lib.enc_bucket_nslots.argtypes = [ctypes.c_void_p]
    lib.enc_bucket_path.restype = ctypes.c_int
    lib.enc_bucket_path.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                    ctypes.POINTER(ctypes.c_char_p), u32p]
    lib.enc_bucket_add_path.restype = ctypes.c_int
    lib.enc_bucket_add_path.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.enc_hash_value.restype = ctypes.c_uint32
    lib.enc_hash_value.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.enc_fnv1a.restype = ctypes.c_uint32
    lib.enc_fnv1a.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
    lib.enc_hash_pair.restype = ctypes.c_uint32
    lib.enc_hash_pair.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                                  ctypes.c_size_t]
    lib.enc_tokenize_schemas.restype = ctypes.c_int
    lib.enc_tokenize_schemas.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
        ctypes.c_uint32, u32p]


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library, or None."""
    global _lib, _load_attempted
    if os.environ.get("KCP_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        lib_path = os.path.join(_NATIVE_DIR, _LIB_NAME)
        try:
            if not os.path.exists(lib_path) or _sources_newer_than_lib(lib_path):
                subprocess.run(
                    ["make", "-s", "-C", _NATIVE_DIR],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(lib_path)
            _declare(lib)
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


class WalEngine:
    """Durable WAL storage engine handle (native walstore.cc).

    Keys and values are bytes; the store layers its
    ``/<resource>/<cluster>/<ns>/<name>`` scheme on top with NUL-joined
    key tuples so prefix scans follow the etcd range-scan idiom
    (docs/investigations/logical-clusters.md:70-74 in the reference).
    """

    def __init__(self, path: str, sync_every: int = 256):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.ws_open(path.encode(), sync_every)
        if not self._h:
            raise OSError(f"ws_open({path!r}) failed")

    def put(self, key: bytes, val: bytes, rv: int) -> None:
        if self._lib.ws_put(self._h, key, len(key), val, len(val), rv) != 0:
            raise OSError(self._lib.ws_last_error(self._h).decode())

    def delete(self, key: bytes, rv: int) -> None:
        if self._lib.ws_del(self._h, key, len(key), rv) != 0:
            raise OSError(self._lib.ws_last_error(self._h).decode())

    def get(self, key: bytes) -> bytes | None:
        val = ctypes.POINTER(ctypes.c_uint8)()
        vlen = ctypes.c_uint32()
        if self._lib.ws_get(self._h, key, len(key), ctypes.byref(val), ctypes.byref(vlen)):
            return ctypes.string_at(val, vlen.value)
        return None

    @property
    def rv(self) -> int:
        return self._lib.ws_rv(self._h)

    @property
    def epoch(self) -> int:
        """Replication epoch persisted in the log (0 = never stamped)."""
        return self._lib.ws_epoch(self._h)

    def set_epoch(self, epoch: int) -> None:
        """Durably stamp a replication epoch (fsynced before return —
        fences and promotions must not be lost to a crash)."""
        if self._lib.ws_set_epoch(self._h, epoch) != 0:
            raise OSError(self._lib.ws_last_error(self._h).decode())

    def set_rv(self, rv: int) -> None:
        """Advance the RV watermark without a mutation record (snapshot
        resync: objects arrive with their own RVs, the barrier carries
        the authoritative watermark)."""
        self._lib.ws_set_rv(self._h, rv)

    def __len__(self) -> int:
        return self._lib.ws_count(self._h)

    def append_batch(self, ops, fsync: bool = False) -> None:
        """Append one group-commit window of records as ONE buffered
        write + at most one fsync. ``ops`` is an iterable of
        ``(key, val, rv)`` tuples — ``val is None`` means delete. With
        ``fsync=False`` the engine's ``sync_every`` batching still
        applies (the KCP_WAL_SYNC=flush policy); a failed commit leaves
        NONE of the window's records in the log."""
        lib = self._lib
        if lib.ws_batch_begin(self._h) != 0:
            raise OSError(lib.ws_last_error(self._h).decode())
        try:
            for key, val, rv in ops:
                if val is None:
                    self.delete(key, rv)
                else:
                    self.put(key, val, rv)
        except BaseException:
            lib.ws_batch_abort(self._h)
            raise
        if lib.ws_batch_commit(self._h, 1 if fsync else 0) != 0:
            raise OSError(lib.ws_last_error(self._h).decode())

    def flush(self) -> None:
        if self._lib.ws_flush(self._h) != 0:
            raise OSError("fsync failed")

    def snapshot(self) -> None:
        if self._lib.ws_snapshot(self._h) != 0:
            raise OSError("snapshot failed")

    def snapshot_stream(self, items) -> None:
        """Compact by streaming (key, value) pairs from the caller —
        used in journal-only mode where the engine keeps no value copy."""
        if self._lib.ws_snapshot_begin(self._h) != 0:
            raise OSError("snapshot begin failed")
        for key, val in items:
            if self._lib.ws_snapshot_add(self._h, key, len(key), val, len(val)) != 0:
                raise OSError("snapshot add failed")
        if self._lib.ws_snapshot_commit(self._h) != 0:
            raise OSError("snapshot commit failed")

    def release_index(self) -> None:
        """Switch to journal-only mode: drop the engine's in-memory copy
        (the host holds the authoritative objects; get/scan go dark)."""
        self._lib.ws_index_release(self._h)

    def scan(self, prefix: bytes = b"") -> Iterator[tuple[bytes, bytes]]:
        cur = self._lib.ws_scan(self._h, prefix, len(prefix))
        try:
            key = ctypes.POINTER(ctypes.c_uint8)()
            val = ctypes.POINTER(ctypes.c_uint8)()
            klen = ctypes.c_uint32()
            vlen = ctypes.c_uint32()
            while self._lib.ws_scan_next(cur, ctypes.byref(key), ctypes.byref(klen),
                                         ctypes.byref(val), ctypes.byref(vlen)):
                yield ctypes.string_at(key, klen.value), ctypes.string_at(val, vlen.value)
        finally:
            self._lib.ws_scan_free(cur)

    def close(self) -> None:
        if self._h:
            self._lib.ws_close(self._h)
            self._h = None


class NativeBucket:
    """Native slot-vocabulary encoder (twin of ops.encode.BucketEncoder)."""

    OVERFLOW = -1

    def __init__(self, capacity: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.capacity = capacity
        self._h = lib.enc_bucket_new(capacity)

    def encode_json(self, json_bytes: bytes, out) -> int:
        """Encode one object's JSON into out (uint32[capacity] numpy).

        Returns 0 ok, -1 overflow, -2/-3 parse errors.
        """
        import numpy as np

        if out.size < self.capacity:
            raise ValueError(
                f"out has {out.size} elements; bucket capacity is {self.capacity}"
            )
        direct = out.flags["C_CONTIGUOUS"] and out.dtype == np.uint32
        buf = out if direct else np.zeros(self.capacity, dtype=np.uint32)
        ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
        rc = self._lib.enc_bucket_encode(self._h, json_bytes, len(json_bytes), ptr)
        if not direct and rc == 0:
            out[: self.capacity] = buf
        return rc

    @property
    def nslots(self) -> int:
        return self._lib.enc_bucket_nslots(self._h)

    def slot_paths(self) -> list[str]:
        out = []
        path = ctypes.c_char_p()
        plen = ctypes.c_uint32()
        for slot in range(self.nslots):
            if self._lib.enc_bucket_path(self._h, slot, ctypes.byref(path), ctypes.byref(plen)):
                out.append(path.value[:plen.value].decode())
        return out

    def add_path(self, path: str) -> int:
        return self._lib.enc_bucket_add_path(self._h, path.encode(), len(path.encode()))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.enc_bucket_free(self._h)
        except Exception:
            pass


def hash_value_native(json_bytes: bytes) -> int:
    lib = load()
    assert lib is not None
    return lib.enc_hash_value(json_bytes, len(json_bytes))


def fnv1a_native(data: bytes, seed: int = 0x811C9DC5) -> int:
    lib = load()
    assert lib is not None
    return lib.enc_fnv1a(data, len(data), seed)


_tok_mod = None
_tok_tried = False


def load_tokenizer():
    """Load (building if needed) the kcptok CPython extension, or None.

    Separate from :func:`load` because the extension needs Python dev
    headers at build time; its absence must not disable the main
    library. Same fallback contract: None means callers use the next
    tier down (the JSON-blob native path, then the Python walk).
    """
    global _tok_mod, _tok_tried
    if os.environ.get("KCP_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _tok_tried:
            return _tok_mod
        _tok_tried = True
        path = os.path.join(_NATIVE_DIR, "kcptok.so")
        try:
            if not os.path.exists(path) or _sources_newer_than_lib(path):
                import sysconfig

                # compile against THIS interpreter's headers — the
                # Makefile's PATH-python3 default could be a different
                # Python whose ABI would segfault on dlopen
                subprocess.run(
                    ["make", "-s", "-C", _NATIVE_DIR, "kcptok.so",
                     f"PYINC={sysconfig.get_paths()['include']}"],
                    check=True, capture_output=True, timeout=120,
                )
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader("kcptok", path)
            spec = importlib.util.spec_from_loader("kcptok", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
            _tok_mod = mod
        except Exception:
            _tok_mod = None
        return _tok_mod


def tokenize_schemas_native(blobs: list[bytes], max_tokens: int):
    """Tokenize a batch of canonical-JSON schemas in one native call.

    Returns a ``[len(blobs), max_tokens]`` uint32 numpy array, or None
    when the library is unavailable or any blob fails to parse (callers
    fall back to the Python walk — same contract as the other native
    accelerators here).
    """
    lib = load()
    if lib is None:
        return None
    import numpy as np

    n = len(blobs)
    if n == 0:
        return np.zeros((0, max_tokens), dtype=np.uint32)
    offsets = np.zeros(n + 1, dtype=np.uint64)
    lengths = np.fromiter((len(b) for b in blobs), dtype=np.uint64, count=n)
    np.cumsum(lengths, out=offsets[1:])
    data = b"".join(blobs)
    out = np.empty((n, max_tokens), dtype=np.uint32)
    rc = lib.enc_tokenize_schemas(
        data,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n,
        max_tokens,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out if rc == 0 else None
