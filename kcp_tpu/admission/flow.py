"""APF-style flow control for the write path.

Kubernetes' API Priority and Fairness (KEP-1040) is the proven design
for the overload shape this server faces: one tenant flooding writes
must not starve the other 9,999. The machinery, scaled to this repo:

- **classification**: every mutating request maps to a *flow*
  ``(tenant, verb-class)`` — the target logical cluster crossed with the
  verb (create/update/delete each get their own bucket, so a create
  flood cannot starve the same tenant's deletes);
- **per-flow token buckets**: each flow refills at ``rate`` tokens/s up
  to ``burst``; a request with no token is rejected immediately with
  429 + a precise ``Retry-After`` computed from the refill rate — the
  flooding tenant is throttled at its budget, not queued unboundedly;
- **shuffle-sharded bounded queues**: requests holding a token but
  finding the global concurrency limit saturated wait in one of ``Q``
  bounded FIFO queues; each flow hashes (seeded, deterministic) to a
  small *hand* of candidate queues and enqueues on the shortest, so a
  misbehaving flow can poison at most its hand while everyone else's
  queues drain normally (the APF shuffle-sharding argument);
- **bounded everything**: a full candidate queue is 429, never an
  unbounded buffer.

The controller is event-loop-affine (the REST handler's serving loop);
the fast path — token available, free concurrency slot, nothing queued —
is a few dict/float ops and never allocates a future. Composition with
PR 2's degraded-mode machinery is by construction: a 429 is an HTTP
answer, so the client-side circuit breaker (transport failures only)
never trips on throttling, and the typed ``TooManyRequestsError`` gives
informers/syncers the pacing hint instead of a blind retry.

Reads never touch this module (zero-cost by omission: the handler only
classifies mutating verbs).
"""

from __future__ import annotations

import math
import os
import random
import time
from collections import deque

from ..faults import maybe_fail
from ..utils.errors import TooManyRequestsError
from ..utils.trace import REGISTRY

VERB_CLASSES = ("create", "update", "delete")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FlowController:
    """Token buckets + shuffle-sharded queues + global concurrency.

    ``concurrency=0`` disables flow control entirely (build_chain then
    wires no controller). All state lives on the serving loop.
    """

    def __init__(self, concurrency: int = 64, rate: float = 500.0,
                 burst: float | None = None, queues: int = 16,
                 queue_depth: int = 32, hand_size: int = 4,
                 seed: int = 0, clock=time.monotonic):
        self.concurrency = int(concurrency)
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else 2 * rate)
        self.queue_depth = int(queue_depth)
        self.hand_size = max(1, min(int(hand_size), int(queues)))
        self.seed = seed
        self._clock = clock
        self._in_flight = 0
        # shuffle shards: deque of (future, flow-id) waiters per queue
        self._queues: list[deque] = [deque() for _ in range(int(queues))]
        self._qdepth = 0  # waiters across all queues
        self._rr = 0  # round-robin dispatch pointer
        # per-flow interned state: plain python floats (the hot path is
        # one request at a time — scalar numpy would cost ufunc dispatch)
        self._fids: dict[tuple[str, str], int] = {}
        self._flow_keys: list[tuple[str, str]] = []
        self._tokens: list[float] = []
        self._last: list[float] = []
        self._hand: list[tuple[int, ...]] = []
        self._wait_hist = REGISTRY.histogram(
            "flow_wait_seconds", "time requests spent queued by flow control")
        self._depth_gauge = REGISTRY.gauge(
            "flow_queue_depth", "requests currently parked in flow queues")
        self._rejected = REGISTRY.counter(
            "flow_rejected_total", "requests rejected 429 by flow control")
        # one bound method reused by every fast-path admit (and by the
        # chain's shared FastTicket) instead of a fresh binding per call
        self._release_cb = self.release

    @classmethod
    def from_env(cls) -> "FlowController | None":
        """KCP_FLOW_* environment knobs; KCP_FLOW_CONCURRENCY=0 = off."""
        concurrency = _env_int("KCP_FLOW_CONCURRENCY", 64)
        if concurrency <= 0:
            return None
        rate = _env_float("KCP_FLOW_RATE", 500.0)
        return cls(
            concurrency=concurrency,
            rate=rate,
            burst=_env_float("KCP_FLOW_BURST", 2 * rate),
            queues=_env_int("KCP_FLOW_QUEUES", 16),
            queue_depth=_env_int("KCP_FLOW_QUEUE_DEPTH", 32),
            hand_size=_env_int("KCP_FLOW_HAND", 4),
            seed=_env_int("KCP_FLOW_SEED", 0),
        )

    # -------------------------------------------------------------- flows

    def _fid(self, tenant: str, verb_class: str) -> int:
        fid = self._fids.get((tenant, verb_class))
        if fid is None:
            fid = len(self._tokens)
            self._fids[(tenant, verb_class)] = fid
            self._flow_keys.append((tenant, verb_class))
            self._tokens.append(self.burst)
            self._last.append(self._clock())
            # deterministic shuffle shard: the flow's hand of candidate
            # queues from a seeded PRNG keyed by the flow identity
            rnd = random.Random(f"{self.seed}:{tenant}:{verb_class}")
            self._hand.append(tuple(
                rnd.sample(range(len(self._queues)), self.hand_size)))
        return fid

    # ------------------------------------------------------------ admit

    def try_acquire(self, tenant: str, verb_class: str):
        """Admit one mutating request. Returns the release callable on
        the fast path (token + free concurrency slot); returns the flow
        id (int) when the caller must ``await queue_wait(fid)``; raises
        TooManyRequestsError (with ``retry_after``) on token exhaustion
        or a full candidate queue. ``admission.flow`` is a KCP_FAULTS
        injection point."""
        maybe_fail("admission.flow")
        fid = self._fids.get((tenant, verb_class))
        if fid is None:
            fid = self._fid(tenant, verb_class)
        tokens_l = self._tokens
        last_l = self._last
        now = self._clock()
        tokens = tokens_l[fid] + (now - last_l[fid]) * self.rate
        burst = self.burst
        if tokens > burst:
            tokens = burst
        last_l[fid] = now
        if tokens < 1.0:
            tokens_l[fid] = tokens
            self._reject(tenant, verb_class,
                         retry_after=(1.0 - tokens) / self.rate)
        tokens_l[fid] = tokens - 1.0
        if self._in_flight < self.concurrency and not self._qdepth:
            # fast path: free slot, nobody queued ahead
            self._in_flight += 1
            return self._release_cb
        q = min((self._queues[i] for i in self._hand[fid]), key=len)
        if len(q) >= self.queue_depth:
            self._reject(tenant, verb_class, retry_after=1.0)
        return fid

    async def queue_wait(self, fid: int):
        """Park in the flow's shortest candidate queue until a released
        slot dispatches us; returns the release callable."""
        import asyncio

        q = min((self._queues[i] for i in self._hand[fid]), key=len)
        if len(q) >= self.queue_depth:
            # the queue filled between try_acquire and here
            tenant, verb_class = self._flow_keys[fid]
            self._reject(tenant, verb_class, retry_after=1.0)
        fut = asyncio.get_running_loop().create_future()
        q.append(fut)
        self._qdepth += 1
        self._depth_gauge.set(self._qdepth)
        # liveness: cancelled waiters (disconnected clients) linger in
        # the queues until popped, so _qdepth can be nonzero with free
        # slots — run a dispatch pass so this waiter never parks behind
        # ghosts when capacity is actually available
        if self._in_flight < self.concurrency:
            self._dispatch()
        t0 = self._clock()
        try:
            await fut
        except asyncio.CancelledError:
            # client went away while queued: either give the slot back
            # (we were already dispatched) or just leave the queue (the
            # dispatcher skips cancelled futures)
            if fut.done() and not fut.cancelled():
                self.release()
            raise
        finally:
            self._wait_hist.observe(self._clock() - t0)
        return self._release_cb

    async def acquire(self, tenant: str, verb_class: str):
        """try_acquire + queue_wait in one call (tests, simple callers)."""
        got = self.try_acquire(tenant, verb_class)
        if isinstance(got, int):
            return await self.queue_wait(got)
        return got

    def _reject(self, tenant: str, verb_class: str, retry_after: float):
        self._rejected.inc()
        err = TooManyRequestsError(
            f'write flow ({tenant}, {verb_class}) is over its budget')
        err.retry_after = max(0.05, math.ceil(retry_after * 20) / 20)
        raise err

    def release(self) -> None:
        """Free a concurrency slot and dispatch the next queued waiter."""
        self._in_flight -= 1
        if self._qdepth:
            self._dispatch()

    def _dispatch(self) -> None:
        """Hand free concurrency slots to queued waiters, round-robin
        across shuffle-shard queues (per-queue FIFO, no queue starves);
        cancelled waiters are skimmed off on the way."""
        while self._in_flight < self.concurrency and self._qdepth:
            dispatched = False
            nq = len(self._queues)
            for off in range(nq):
                q = self._queues[(self._rr + off) % nq]
                while q:
                    fut = q.popleft()
                    self._qdepth -= 1
                    if fut.cancelled():
                        continue
                    self._rr = (self._rr + off + 1) % nq
                    self._in_flight += 1
                    fut.set_result(None)
                    dispatched = True
                    break
                if dispatched:
                    break
            if not dispatched:
                break
        self._depth_gauge.set(self._qdepth)
