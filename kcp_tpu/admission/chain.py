"""The pluggable admission chain: mutating defaulting → validation → quota.

The missing stage of the REST write path — the reference's forked
apiserver inherits Kubernetes admission between authz and storage;
here the chain is wired into ``RestHandler._serve_resource`` the same
way, with this repo's disciplines: every plugin declares the
``(verb, resource)`` sets it intercepts (the routing table is
precomputed, so non-intercepted writes touch two dict lookups and reads
never touch the chain at all), the whole chain is metered
(``admission_seconds`` / ``admission_denied_total``) and
fault-injectable (``admission.chain`` / ``admission.quota`` /
``admission.flow`` KCP_FAULTS points).

Protocol: the handler calls ``ticket = await chain.admit(...)`` before
the store verb, then ``ticket.ok()`` on success or ``ticket.fail()`` on
any failure — the ticket carries the quota reservation
(commit/rollback) and the flow-control concurrency slot, so neither can
leak past one request.

``KCP_ADMISSION=0`` disables the chain entirely (``build_chain``
returns None and the handler's write path is byte-identical to the
pre-admission server).
"""

from __future__ import annotations

import os
import time

from ..faults import maybe_fail
from ..utils.errors import ApiError, InvalidError
from ..utils.trace import REGISTRY

from .flow import FlowController
from .quota import QUOTA_RESOURCE, QuotaLedger, QuotaPlugin, normalize_hard

WRITE_VERBS = frozenset({"create", "update", "delete"})


class _NoopTicket:
    __slots__ = ()

    def ok(self) -> None:
        pass

    def fail(self) -> None:
        pass

    def split_for_window(self):
        """Group-commit form of settle: nothing to defer."""
        return None, None


NOOP_TICKET = _NoopTicket()


class FastTicket:
    """Reusable release-only ticket: the common admitted write (no
    reservation, no after-callback) has exactly one obligation — free
    its flow slot — and the release callable is the same bound method
    for every request through one chain, so ONE instance serves them
    all. The handler settles each ticket exactly once by construction
    (ok on success xor fail on failure), which is what makes sharing
    safe; anything stateful gets a real :class:`Ticket`."""

    __slots__ = ("_release",)

    def __init__(self, release):
        self._release = release

    def ok(self) -> None:
        self._release()

    fail = ok

    def split_for_window(self):
        """Group-commit form of settle: free the flow slot now (the
        window linger must not hold concurrency), nothing to defer."""
        self._release()
        return None, None


class Ticket:
    """One admitted write's obligations: settle exactly once."""

    __slots__ = ("_reservation", "_release", "_after", "_done")

    def __init__(self, reservation=None, release=None, after=None):
        self._reservation = reservation
        self._release = release
        self._after = after
        self._done = False

    def ok(self) -> None:
        if self._done:
            return
        self._done = True
        if self._reservation is not None:
            self._reservation.commit()
        if self._after is not None:
            self._after()
        if self._release is not None:
            self._release()

    def fail(self) -> None:
        if self._done:
            return
        self._done = True
        if self._reservation is not None:
            self._reservation.rollback()
        if self._release is not None:
            self._release()

    def split_for_window(self):
        """Group-commit form of settle: free the flow slot NOW and hand
        the stateful half — (quota reservation, after-hook) — to the
        caller's commit window, which settles a whole window's
        reservations in one batched ledger pass
        (admission/quota.settle_batch). Marks the ticket done: the
        window owns the rest."""
        if self._done:
            return None, None
        self._done = True
        if self._release is not None:
            self._release()
        return self._reservation, self._after


class DefaultingPlugin:
    """Mutating admission: per-resource defaulters edit the body in
    place before validation sees it. ``resources`` is exactly the
    registered set, so unregistered resources never route here."""

    name = "defaulting"
    verbs = frozenset({"create", "update"})

    def __init__(self):
        self._defaulters: dict[str, list] = {}
        self.register(QUOTA_RESOURCE, _default_resourcequota)

    @property
    def resources(self) -> frozenset:
        return frozenset(self._defaulters)

    def register(self, resource: str, fn) -> None:
        self._defaulters.setdefault(resource, []).append(fn)

    def admit(self, verb: str, resource: str, cluster: str,
              namespace: str, obj: dict | None):
        if obj is None:
            return None
        for fn in self._defaulters.get(resource, ()):
            fn(obj)
        return None


def _default_resourcequota(obj: dict) -> None:
    """Normalize ``spec.hard`` to canonical ``count/<resource>: int``
    form so the ledger (and every reader) sees one spelling."""
    spec = obj.get("spec")
    if not isinstance(spec, dict):
        return
    hard = spec.get("hard")
    if not isinstance(hard, dict):
        return
    try:
        normalized = normalize_hard(hard)
    except (ValueError, TypeError):
        return  # validation rejects it with a real message
    spec["hard"] = {f"count/{res}": n for res, n in sorted(normalized.items())}


class ValidationPlugin:
    """Non-mutating admission: reject malformed writes with 422 before
    they reach storage."""

    name = "validation"
    verbs = frozenset({"create", "update"})
    resources = None  # every resource: the generic metadata checks

    def admit(self, verb: str, resource: str, cluster: str,
              namespace: str, obj: dict | None):
        if obj is None:
            return None
        meta = obj.get("metadata")
        if meta is not None and not isinstance(meta, dict):
            raise InvalidError("metadata must be an object")
        if verb == "create":
            meta = meta or {}
            if not meta.get("name") and not meta.get("generateName"):
                raise InvalidError("metadata.name is required")
        if resource == QUOTA_RESOURCE:
            spec = obj.get("spec")
            if spec is not None and not isinstance(spec, dict):
                raise InvalidError("spec must be an object")
            hard = (spec or {}).get("hard")
            if hard is not None:
                if not isinstance(hard, dict):
                    raise InvalidError("spec.hard must be a map")
                try:
                    normalize_hard(hard)
                except (ValueError, TypeError) as e:
                    raise InvalidError(f"malformed spec.hard: {e}") from e
        return None


class AdmissionChain:
    """Ordered plugins + optional flow control, with precomputed
    (verb, resource) routing."""

    def __init__(self, plugins, flow: FlowController | None = None,
                 ledger: QuotaLedger | None = None, store=None):
        self.plugins = list(plugins)
        self.flow = flow
        self.ledger = ledger
        self._store = store
        # the one ticket shape the uncontended happy path ever needs
        self._fast_ticket = (FastTicket(flow.release) if flow is not None
                             else NOOP_TICKET)
        self._route: dict[tuple[str, str], tuple] = {}
        self._seconds = REGISTRY.histogram(
            "admission_seconds", "time spent in the write admission chain")
        self._denied = REGISTRY.counter(
            "admission_denied_total",
            "writes denied by the admission chain (quota, validation, flow)")

    def defaulting(self) -> DefaultingPlugin | None:
        for p in self.plugins:
            if isinstance(p, DefaultingPlugin):
                return p
        return None

    def _plugins_for(self, verb: str, resource: str) -> tuple:
        key = (verb, resource)
        route = self._route.get(key)
        if route is None:
            route = tuple(
                p for p in self.plugins
                if verb in p.verbs
                and (p.resources is None or resource in p.resources))
            self._route[key] = route
        return route

    def admit_nowait(self, verb: str, resource: str, cluster: str,
                     namespace: str, obj: dict | None):
        """Run the chain for one mutating request. Raises ApiError on
        denial (403 quota, 422 validation, 429 flow, injected 503).
        Returns the Ticket to settle around the store verb — or, only
        when flow control must queue the request, a coroutine resolving
        to that Ticket. The uncontended path is fully synchronous: no
        coroutine, no future (the handler awaits per-write otherwise,
        and that alone costs more than the whole chain)."""
        t0 = time.perf_counter()
        try:
            maybe_fail("admission.chain")
            release = None
            flow = self.flow
            if flow is not None:
                got = flow.try_acquire(cluster, verb)
                if type(got) is int:
                    return self._admit_queued(
                        got, verb, resource, cluster, namespace, obj, t0)
                release = got
        except ApiError:
            self._denied.inc()
            self._seconds.observe(time.perf_counter() - t0)
            raise
        return self._run_plugins(verb, resource, cluster, namespace, obj,
                                 release, t0)

    async def _admit_queued(self, fid: int, verb: str, resource: str,
                            cluster: str, namespace: str, obj: dict | None,
                            t0: float) -> Ticket:
        try:
            release = await self.flow.queue_wait(fid)
        except ApiError:
            self._denied.inc()
            self._seconds.observe(time.perf_counter() - t0)
            raise
        return self._run_plugins(verb, resource, cluster, namespace, obj,
                                 release, t0)

    def _run_plugins(self, verb, resource, cluster, namespace, obj,
                     release, t0) -> Ticket:
        reservation = None
        try:
            route = self._route.get((verb, resource))
            if route is None:
                route = self._plugins_for(verb, resource)
            for p in route:
                r = p.admit(verb, resource, cluster, namespace, obj)
                if r is not None:
                    reservation = r
            after = None
            if resource == QUOTA_RESOURCE and self.ledger is not None:
                # a ResourceQuota write re-derives that cluster's hard
                # limits synchronously once the store verb lands (the
                # recount controller covers non-REST writers)
                store, ledger = self._store, self.ledger
                after = lambda: ledger.resync_limits(store, cluster)  # noqa: E731
        except BaseException as e:
            if reservation is not None:
                reservation.rollback()
            if release is not None:
                release()
            if isinstance(e, ApiError):
                self._denied.inc()
            self._seconds.observe(time.perf_counter() - t0)
            raise
        self._seconds.observe(time.perf_counter() - t0)
        if reservation is None and after is None:
            # nothing stateful to settle: the shared release-only ticket
            return self._fast_ticket if release is not None else NOOP_TICKET
        return Ticket(reservation, release, after)

    async def admit(self, verb: str, resource: str, cluster: str,
                    namespace: str, obj: dict | None) -> Ticket:
        """Awaitable form of :meth:`admit_nowait` (tests, simple callers)."""
        got = self.admit_nowait(verb, resource, cluster, namespace, obj)
        return got if hasattr(got, "ok") else await got


def enabled() -> bool:
    return os.environ.get("KCP_ADMISSION", "1").lower() not in (
        "0", "false", "off")


def build_chain(store, flow: FlowController | None = None,
                ledger: QuotaLedger | None = None) -> AdmissionChain | None:
    """The server's default chain: defaulting → validation → quota, with
    env-configured flow control. Returns None when ``KCP_ADMISSION=0``.

    Remote-store frontends get no quota plugin — usage/limits are
    enforced once, by the storage backend's own chain (the same
    division of labor as RVs and conflicts); local flow control still
    sheds load before it ever reaches the backend.
    """
    if not enabled():
        return None
    if flow is None:
        flow = FlowController.from_env()
    plugins: list = [DefaultingPlugin(), ValidationPlugin()]
    is_remote = getattr(store, "is_remote", False)
    if not is_remote:
        if ledger is None:
            ledger = QuotaLedger()
        ledger.attach(store)
        plugins.append(QuotaPlugin(ledger))
    else:
        ledger = None
    return AdmissionChain(plugins, flow=flow, ledger=ledger, store=store)
