"""Vectorized quota ledgers: per-(cluster, resource) object-count budgets.

The reference carves per-workspace policy/quota out as its own subsystem
(docs/investigations/self-service-policy.md); Kubernetes enforces it with
the ResourceQuota admission plugin — reserve against the quota *before*
the storage write, commit after, so concurrent writers can never
oversubscribe a hard limit. This module is that protocol built the way
this repo builds everything: usage, in-flight reservations and hard
limits are **numpy arrays over interned (cluster, resource) ids** (the
same interning trick as the store's vectorized watch fan-out), so the
recount/repair pass and the exported gauges are single vector ops over
10k tenants instead of a python dict walk.

Three cooperating pieces:

- :class:`QuotaLedger` — the arrays plus the reserve → commit/rollback
  protocol. *Usage* is advanced by a store mutation hook
  (``LogicalStore.set_usage_hook``): the store's object map is the source
  of truth, so writes that bypass the REST surface (in-process
  controllers, WAL restore) are counted too. *Reservations* only live
  across one admission→write window and guarantee
  ``usage + reserved <= hard`` at reserve time.
- :class:`QuotaPlugin` — the admission-chain plugin: reserves one object
  on every create; denial is a Kubernetes-style 403
  (:class:`~kcp_tpu.utils.errors.ForbiddenError`). ``admission.quota``
  is a KCP_FAULTS injection point fired *after* the reservation is
  booked, so injected failures exercise the rollback discipline.
- :class:`UsageRecountController` — registered like the existing
  reconcilers: watches ``resourcequotas`` to apply limit changes and
  periodically recounts usage from the store's secondary index (cheap:
  bucket lengths, no object walk) to repair any drift from deletes,
  crashes or out-of-band mutation.

Limits come from ``ResourceQuota``-style objects living in the store::

    {"apiVersion": "v1", "kind": "ResourceQuota",
     "metadata": {"name": "budget", "namespace": "default"},
     "spec": {"hard": {"count/configmaps": 100, "secrets": 10}}}

``spec.hard`` keys are ``count/<resource>`` (bare resource names are
normalized to that form by the defaulting plugin); several quota objects
in one cluster combine by minimum. Scope here is the logical cluster,
not the namespace — the ledger is keyed (cluster, resource).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time

import numpy as np

from ..analysis.sanitize import make_lock
from ..faults import maybe_fail
from ..utils.errors import ForbiddenError
from ..utils.trace import REGISTRY

log = logging.getLogger(__name__)

QUOTA_RESOURCE = "resourcequotas"
UNLIMITED = -1


def normalize_hard(hard: dict) -> dict[str, int]:
    """Canonical ``{resource: count}`` form of a ``spec.hard`` mapping:
    ``count/<resource>`` prefixes stripped, values coerced to int.
    Raises ValueError on non-integer or negative values."""
    out: dict[str, int] = {}
    for key, val in (hard or {}).items():
        res = key[len("count/"):] if key.startswith("count/") else key
        n = int(val)
        if n < 0:
            raise ValueError(f"quota for {key!r} is negative ({n})")
        # several keys can normalize to one resource; minimum wins
        out[res] = min(out.get(res, n), n)
    return out


class Reservation:
    """One in-flight admission reservation; commit or rollback exactly
    once (idempotent — the second call is a no-op)."""

    __slots__ = ("_ledger", "_idx", "_delta", "_done")

    def __init__(self, ledger: "QuotaLedger", idx: int, delta: int):
        self._ledger = ledger
        self._idx = idx
        self._delta = delta
        self._done = False

    def commit(self) -> None:
        """The write landed: usage was advanced by the store hook, so the
        reservation simply retires."""
        self._settle(rollback=False)

    def rollback(self) -> None:
        """The write failed (or admission aborted after reserving): free
        the reserved headroom."""
        self._settle(rollback=True)

    def _settle(self, rollback: bool) -> None:
        if self._done:
            return
        self._done = True
        self._ledger._release(self._idx, self._delta, rollback)


def settle_batch(reservations, rollback: bool = False) -> None:
    """Settle one commit window's reservations with ONE ledger lock
    acquisition per ledger — the group-commit form of
    :meth:`Reservation.commit` / :meth:`Reservation.rollback`. Already-
    settled (or None) entries are skipped, matching the per-reservation
    idempotence."""
    by_ledger: dict[int, tuple["QuotaLedger", list[tuple[int, int]]]] = {}
    for r in reservations:
        if r is None or r._done:
            continue
        r._done = True
        ent = by_ledger.get(id(r._ledger))
        if ent is None:
            ent = by_ledger[id(r._ledger)] = (r._ledger, [])
        ent[1].append((r._idx, r._delta))
    for ledger, items in by_ledger.values():
        ledger._release_batch(items, rollback)


class QuotaLedger:
    """Vectorized (cluster, resource) usage/limit ledger.

    Thread-safe: admission can reserve from executor threads while the
    recount controller repairs on the serving loop. All hot-path work is
    O(1) — one lock, one interned id, a few scalar array ops."""

    def __init__(self, cap: int = 64):
        self._lock = make_lock("quota.ledger")
        self._idx: dict[tuple[str, str], int] = {}  # (cluster, resource)->i
        self._keys: list[tuple[str, str]] = []
        # usage + hard limits: the vectorized state (recount and gauge
        # export are single vector ops). Reservations are transient
        # near-always-zero scalars, so they stay a plain list — python
        # int ops beat numpy scalar dispatch ~5x on the admit hot path.
        self._usage = np.zeros(cap, np.int64)
        self._reserved: list[int] = [0] * cap
        self._hard = np.full(cap, UNLIMITED, np.int64)
        # clusters currently holding any hard limit — the set the limit
        # resync has to revisit when quota objects disappear
        self._limited_clusters: set[str] = set()
        self._store = None
        # device-side usage lane: per-key live-row counts computed by
        # the fused fleet batch's per-segment counters (FusedCore
        # forwards them on every collect) — admission accounting riding
        # the device batch instead of a host-side pass
        self._device_counts: dict[int, int] = {}
        self._device_stamp = float("-inf")

    # ---------------------------------------------------------- interning

    def _slot(self, cluster: str, resource: str) -> int:
        """Interned id for (cluster, resource); caller holds the lock."""
        i = self._idx.get((cluster, resource))
        if i is None:
            i = len(self._keys)
            if i >= self._usage.size:
                grow = self._usage.size * 2
                self._usage = np.resize(self._usage, grow)
                self._reserved.extend([0] * (grow - len(self._reserved)))
                hard = np.full(grow, UNLIMITED, np.int64)
                hard[:i] = self._hard[:i]
                self._hard = hard
                self._usage[i:] = 0
            self._usage[i] = 0
            self._reserved[i] = 0
            self._hard[i] = UNLIMITED
            self._idx[(cluster, resource)] = i
            self._keys.append((cluster, resource))
        return i

    # ---------------------------------------------------------- protocol

    def reserve(self, cluster: str, resource: str,
                delta: int = 1) -> Reservation | None:
        """Book headroom for ``delta`` objects or raise 403 Forbidden.

        The oversubscription guard: ``usage + reserved + delta`` must fit
        under the hard limit *including every other writer's in-flight
        reservation*, so N concurrent creates against the last free slot
        admit exactly one.

        Unlimited keys return None — there is nothing to oversubscribe,
        the usage hook still counts, and the admit hot path skips the
        Reservation allocation and the commit round-trip entirely (a
        limit set mid-flight binds from the next reserve, the same
        eventual consistency its source ResourceQuota object has)."""
        with self._lock:
            i = self._slot(cluster, resource)
            # .item(): ~4x cheaper than `arr[i] += d` ufunc dispatch —
            # this runs on every admitted create
            hard = self._hard.item(i)
            if hard == UNLIMITED:
                return None
            if delta > 0:
                used = self._usage.item(i) + self._reserved[i]
                if used + delta > hard:
                    REGISTRY.counter(
                        "quota_denied_total",
                        "writes denied by the quota admission plugin").inc()
                    raise ForbiddenError(
                        f'exceeded quota in cluster "{cluster}": requested '
                        f"{delta} {resource}, used {used}, limited {hard}")
            self._reserved[i] += delta
        return Reservation(self, i, delta)

    def _release(self, i: int, delta: int, rollback: bool) -> None:
        with self._lock:
            self._reserved[i] -= delta
        if rollback:
            REGISTRY.counter(
                "quota_rollback_total",
                "quota reservations rolled back (failed writes)").inc()

    def _release_batch(self, items: list[tuple[int, int]],
                       rollback: bool) -> None:
        """One commit window's reservation releases under one lock
        acquisition (:func:`settle_batch`)."""
        with self._lock:
            for i, delta in items:
                self._reserved[i] -= delta
        if rollback and items:
            REGISTRY.counter(
                "quota_rollback_total",
                "quota reservations rolled back (failed writes)").inc(
                len(items))
        REGISTRY.counter(
            "quota_window_settled_total",
            "quota reservations settled by a batched per-commit-window "
            "ledger pass instead of one lock round trip per write").inc(
            len(items))

    # -------------------------------------------------------- usage hook

    def record(self, resource: str, cluster: str, delta: int) -> None:
        """Store mutation hook: the object map changed by ``delta``
        (+1 insert, -1 remove). Signature matches
        ``LogicalStore.set_usage_hook``."""
        with self._lock:
            i = self._slot(cluster, resource)
            used = self._usage.item(i) + delta
            self._usage[i] = used
            if used < 0:
                # must be impossible (the store only removes what exists);
                # counted rather than clamped so tests can assert on it
                REGISTRY.counter(
                    "quota_ledger_negative_total",
                    "ledger usage observed below zero (accounting bug)").inc()

    # ------------------------------------------------------------ limits

    def set_hard(self, cluster: str, resource: str, limit: int) -> None:
        with self._lock:
            self._hard[self._slot(cluster, resource)] = limit
        if limit != UNLIMITED:
            self._limited_clusters.add(cluster)

    def resync_limits(self, store, cluster: str) -> None:
        """Re-derive ``cluster``'s hard limits from its live ResourceQuota
        objects (minimum across objects; resources no longer mentioned go
        unlimited). Runs on the store's loop thread."""
        desired: dict[str, int] = {}
        bucket = store._buckets.get(QUOTA_RESOURCE, {}).get(cluster, {})
        for ns_objs in bucket.values():
            for obj in ns_objs.values():
                try:
                    hard = normalize_hard((obj.get("spec") or {}).get("hard"))
                except (ValueError, TypeError, AttributeError):
                    continue  # validation rejects these on the REST path
                for res, n in hard.items():
                    desired[res] = min(desired.get(res, n), n)
        with self._lock:
            for (c, res), i in self._idx.items():
                if c == cluster:
                    self._hard[i] = desired.pop(res, UNLIMITED)
            for res, n in desired.items():
                self._hard[self._slot(cluster, res)] = n
            limited = any(self._hard[i] != UNLIMITED
                          for (c, _r), i in self._idx.items() if c == cluster)
        if limited:
            self._limited_clusters.add(cluster)
        else:
            self._limited_clusters.discard(cluster)
        self._export_gauges()

    def resync_all_limits(self, store) -> None:
        clusters = set(store._buckets.get(QUOTA_RESOURCE, {}))
        for cluster in clusters | set(self._limited_clusters):
            self.resync_limits(store, cluster)

    # ----------------------------------------------- device-count lane

    def ingest_device_counts(self, counts: dict[tuple[str, str], int]) -> None:
        """Fold the fleet batch's device-side per-segment counters into
        the ledger's device-usage lane.

        ``counts`` maps (cluster, resource) to the number of live synced
        rows the fused step counted for that key THIS tick — computed on
        device as a segment-sum riding the same batch as the reconcile
        decisions, so it costs the serving path nothing. The lane feeds
        (1) the ``quota_usage_device`` gauge, (2) drift detection
        (``quota_device_drift_total`` counts keys where the device lane
        and the ledger disagree — a synced-but-miscounted tenant), and
        (3) the recount controller's fast path: when every limited key
        has a fresh, agreeing device count, the periodic host-side
        recount walk is skipped. The store-derived host recount remains
        the repair authority — a section's device count equals the store
        count exactly when every object of the resource is labeled for
        sync, and any disagreement falls back to the host pass."""
        now = time.monotonic()
        drift = 0
        with self._lock:
            for key, n in counts.items():
                i = self._slot(*key)
                self._device_counts[i] = int(n)
                if self._usage.item(i) != n:
                    drift += 1
            self._device_stamp = now
        REGISTRY.gauge(
            "quota_usage_device",
            "live synced rows counted on-device by the fleet batch's "
            "per-segment counters").set(sum(counts.values()))
        if drift:
            REGISTRY.counter(
                "quota_device_drift_total",
                "device-counted keys disagreeing with ledger usage").inc(
                drift)

    def device_usage_of(self, cluster: str, resource: str) -> int | None:
        """The device-lane count for a key (None = never reported)."""
        with self._lock:
            i = self._idx.get((cluster, resource))
            return self._device_counts.get(i) if i is not None else None

    def device_counts_agree(self, max_age: float) -> bool:
        """True when every limited key has a device-lane count no older
        than ``max_age`` seconds that equals ledger usage — the recount
        controller's evidence that accounting is riding the fleet batch
        and the host-side recount walk can be skipped this cycle."""
        with self._lock:
            if time.monotonic() - self._device_stamp > max_age:
                return False
            limited = [i for i in range(len(self._keys))
                       if self._hard[i] != UNLIMITED]
            if not limited:
                return False
            for i in limited:
                dc = self._device_counts.get(i)
                if dc is None or dc != self._usage.item(i):
                    return False
        return True

    # ----------------------------------------------------------- repair

    def recount(self, store) -> int:
        """Set usage to the store's true per-bucket counts; returns how
        many keys drifted (0 in a healthy system). One vector compare
        over the whole ledger. Runs on the store's loop thread."""
        desired = {(c, r): n for (r, c), n in store.counts().items()}
        with self._lock:
            n = len(self._keys)
            for key in desired:
                if key not in self._idx:
                    self._slot(*key)
            n = len(self._keys)
            want = np.fromiter(
                (desired.get(k, 0) for k in self._keys), np.int64, n)
            drift = int((self._usage[:n] != want).sum())
            if drift:
                REGISTRY.counter(
                    "quota_recount_repairs_total",
                    "ledger entries repaired by the usage recount").inc(drift)
                log.warning("quota recount repaired %d drifted entries", drift)
                self._usage[:n] = want
        self._export_gauges()
        return drift

    def attach(self, store) -> None:
        """Wire this ledger to a LogicalStore: usage hook on every
        mutation, then a recount + limit resync so a WAL-restored store
        starts with correct usage and live limits."""
        self._store = store
        store.set_usage_hook(self.record)
        self.recount(store)
        self.resync_all_limits(store)

    # ------------------------------------------------------ introspection

    def peek(self, cluster: str, resource: str) -> tuple[int, int, int]:
        """(usage, reserved, hard) — test/debug accessor."""
        with self._lock:
            i = self._idx.get((cluster, resource))
            if i is None:
                return (0, 0, UNLIMITED)
            return (int(self._usage[i]), int(self._reserved[i]),
                    int(self._hard[i]))

    def usage_of(self, cluster: str, resource: str) -> int:
        return self.peek(cluster, resource)[0]

    def snapshot(self) -> dict[tuple[str, str], tuple[int, int, int]]:
        with self._lock:
            n = len(self._keys)
            return {k: (int(self._usage[i]), int(self._reserved[i]),
                        int(self._hard[i]))
                    for i, k in enumerate(self._keys[:n])}

    def _export_gauges(self) -> None:
        """`quota_usage`: total usage across *limited* keys (per-key
        gauges stay bounded by the operator-created quota objects, not by
        tenant count)."""
        with self._lock:
            n = len(self._keys)
            limited = self._hard[:n] != UNLIMITED
            total = int(self._usage[:n][limited].sum())
        REGISTRY.gauge(
            "quota_usage",
            "objects counted against a hard quota limit").set(total)
        REGISTRY.gauge(
            "quota_limited_keys",
            "(cluster, resource) pairs holding a hard limit").set(
            int(limited.sum()))


class QuotaPlugin:
    """Admission plugin: reserve one object per create against the
    ledger. ``admission.quota`` faults fire after the reservation so
    injected errors exercise rollback."""

    name = "quota"
    verbs = frozenset({"create"})
    resources = None  # every resource is countable

    def __init__(self, ledger: QuotaLedger):
        self.ledger = ledger

    def admit(self, verb: str, resource: str, cluster: str,
              namespace: str, obj: dict | None) -> Reservation | None:
        res = self.ledger.reserve(cluster, resource, 1)
        try:
            maybe_fail("admission.quota")
        except BaseException:
            if res is not None:
                res.rollback()
            raise
        return res


class UsageRecountController:
    """The drift-repair reconciler, registered like the other in-process
    controllers (server.py post-start hook): a resourcequotas informer
    applies limit changes promptly (covering in-process writes that
    bypass the REST chain's synchronous resync), and a periodic recount
    repairs usage drift from crashes or out-of-band mutation."""

    def __init__(self, client, ledger: QuotaLedger, store,
                 period: float = 5.0):
        from ..client import Informer
        from ..reconciler.controller import Controller

        self.client = client
        self.ledger = ledger
        self.store = store
        self.period = period
        self.informer = Informer(client, QUOTA_RESOURCE)
        self.controller = Controller("quota-recount", self._process)
        self.informer.add_handler(self._on_event)
        self._task: asyncio.Task | None = None

    def _on_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        m = (new or old)["metadata"]
        self.controller.enqueue((m.get("clusterName", ""),))

    async def _process(self, item) -> None:
        (cluster,) = item
        self.ledger.resync_limits(self.store, cluster)

    async def _recount_loop(self) -> None:
        while True:
            await asyncio.sleep(self.period)
            if self.ledger.device_counts_agree(2 * self.period):
                # admission accounting rode the fused fleet batch this
                # cycle: every limited key has a fresh device-side count
                # agreeing with the ledger, so the host-side recount
                # walk has nothing to repair — skip it (metered)
                REGISTRY.counter(
                    "quota_recount_skipped_total",
                    "periodic host recounts skipped because the fleet "
                    "batch's device counters already agree").inc()
            else:
                self.ledger.recount(self.store)
            self.ledger.resync_all_limits(self.store)

    async def start(self) -> None:
        await self.informer.start()
        await self.controller.start(1)
        self._task = asyncio.create_task(self._recount_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.controller.stop()
        await self.informer.stop()
