"""Multi-tenant admission & flow control for the REST write path.

Three layers between authz and the store verbs (see chain.py, quota.py,
flow.py): a pluggable admission chain (mutating defaulting → validation
→ quota), vectorized per-(cluster, resource) quota ledgers with a
reserve → commit/rollback protocol, and APF-style flow control (per-flow
token buckets + shuffle-sharded bounded queues + a global concurrency
limit, overflow answered 429 + Retry-After).
"""

from .chain import (
    NOOP_TICKET,
    AdmissionChain,
    DefaultingPlugin,
    Ticket,
    ValidationPlugin,
    build_chain,
    enabled,
)
from .flow import FlowController
from .quota import (
    QUOTA_RESOURCE,
    QuotaLedger,
    QuotaPlugin,
    Reservation,
    UsageRecountController,
    normalize_hard,
)

__all__ = [
    "NOOP_TICKET",
    "AdmissionChain",
    "DefaultingPlugin",
    "FlowController",
    "QUOTA_RESOURCE",
    "QuotaLedger",
    "QuotaPlugin",
    "Reservation",
    "Ticket",
    "UsageRecountController",
    "ValidationPlugin",
    "build_chain",
    "enabled",
    "normalize_hard",
]
