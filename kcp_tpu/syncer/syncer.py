"""Syncer: per-cluster sync session over a set of resources.

The analog of the reference's Syncer (pkg/syncer/syncer.go:46-64
StartSyncer: one spec controller + one status controller per registered
cluster). Here a Syncer owns one :class:`BatchSyncEngine` per GVR — each
engine computes both sync directions in one batched program.

Parity details:
- resources that don't exist yet raise RetryableError, so the caller's
  workqueue retries forever instead of burning its 5-retry budget
  (syncer.go:143-215 getAllGVRs + RetryableError)
- push mode runs these engines in-process; pull mode packages the same
  code to run inside the physical cluster (cli/syncer_main.py)
"""

from __future__ import annotations

import asyncio
import logging

from ..apis.scheme import GVR
from ..client import Client
from ..utils.errors import RetryableError

from .engine import BatchSyncEngine

log = logging.getLogger(__name__)


def discover_gvrs(client: Client, resources: list[str]) -> list[str]:
    """Resolve requested resource names against the upstream's served set.

    Raises RetryableError while any requested resource is not served yet
    (e.g. its negotiated CRD has not been published) — mirroring
    getAllGVRs' retry-until-discovered contract.
    """
    served = set(client.resources())
    missing = [r for r in resources if GVR.parse(r).storage_name not in served]
    if missing:
        raise RetryableError(f"resources not served yet: {missing}")
    return [GVR.parse(r).storage_name for r in resources]


class Syncer:
    def __init__(
        self,
        upstream: Client,
        downstream: Client,
        resources: list[str],
        cluster_id: str,
        backend: str = "tpu",
        mesh=None,
        resync_period: float | None = None,
    ):
        self.cluster_id = cluster_id
        self.resources = list(resources)
        kw = {}
        if resync_period is not None:
            # the missed-event / dropped-key safety net (reference:
            # resyncPeriod, pkg/syncer/syncer.go:27) — tunable from the
            # top-level API so operators can trade heal latency for churn
            kw["resync_period"] = resync_period
        self.engines = [
            BatchSyncEngine(upstream, downstream, gvr, cluster_id,
                            backend=backend, mesh=mesh, **kw)
            for gvr in resources
        ]
        self._started = False

    async def start(self) -> None:
        await asyncio.gather(*(e.start() for e in self.engines))
        self._started = True
        log.info("syncer for cluster %s started (%d resources)",
                 self.cluster_id, len(self.engines))

    async def stop(self) -> None:
        if self._started:
            await asyncio.gather(*(e.stop() for e in self.engines))
            self._started = False

    # observability: aggregate convergence + throughput over engines
    def stats(self) -> dict:
        # fused engines sharing a bucket share its tick counter — count
        # each bucket once, not once per engine
        ticks, seen = 0, set()
        for e in self.engines:
            if e.fused and e._section is not None:
                b = e._section.bucket
                if id(b) not in seen:
                    seen.add(id(b))
                    ticks += b.stats["ticks"]
            else:
                ticks += e.stats["ticks"]
        applied = sum(e.stats["decisions_applied"] for e in self.engines)
        samples = [s for e in self.engines for s in e.convergence_samples]
        samples.sort()
        p99 = samples[int(len(samples) * 0.99)] if samples else None
        return {
            "cluster": self.cluster_id,
            "ticks": ticks,
            "decisions_applied": applied,
            "convergence_p99_s": p99,
        }


async def start_syncer(
    upstream: Client,
    downstream: Client,
    resources: list[str],
    cluster_id: str,
    backend: str = "tpu",
    mesh=None,
    resync_period: float | None = None,
) -> Syncer:
    """Push-mode entry point (reference: StartSyncer, syncer.go:46-64).

    Validates the resource set via discovery first (retryable while the
    upstream does not serve a requested resource yet). ``mesh`` shards
    the fused serving core's buckets over a device mesh
    (parallel.mesh.make_mesh); None uses the process serving mesh.
    """
    discover_gvrs(upstream, resources)
    s = Syncer(upstream, downstream, resources, cluster_id, backend=backend,
               mesh=mesh, resync_period=resync_period)
    await s.start()
    return s
