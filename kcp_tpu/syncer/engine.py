"""BatchSyncEngine — the vectorized spec<->status sync loop.

The reference runs two controllers per (cluster, resource-set): a spec
syncer (kcp -> physical, pkg/syncer/specsyncer.go) and a status syncer
(physical -> kcp, pkg/syncer/statussyncer.go), each deep-diffing objects
one goroutine at a time. Here both directions are lanes of ONE batched
device program per (cluster, GVR):

  informer deltas (both sides)
        -> host encode (hash tensors)            ops/encode.py
        -> device scatter into resident mirrors  ops/diff.apply_deltas
        -> device 3-way diff over ALL rows       ops/diff.sync_decisions
        -> non-NOOP rows home to host
        -> host verifies + applies patches with optimistic concurrency

The mirrors are *device-resident* in the tpu backend: host numpy copies
are the staging/rebuild area, but steady-state ticks ship only the padded
delta batch to the device and scatter there (the TPU sits behind a
host<->device link — re-uploading a 100k-row mirror per tick would be
~50MB of transfer and 1000x slower than the kernel itself).

Running the diff over the full resident mirror every tick makes the loop
level-triggered: a tick converges *everything* currently out of sync, not
just the keys that woke it. Two safety nets bound hash-collision damage:
every device decision is re-verified against the real objects before a
write (the host escape hatch), and a periodic informer resync replays the
caches (reference: resyncPeriod, pkg/syncer/syncer.go:27).

Decision application parity with the reference:
- CREATE/UPDATE downstream: strip volatile metadata + ownerReferences +
  status, ensure namespace, create-then-update-on-conflict
  (specsyncer.go:86-132)
- DELETE downstream on upstream deletion (specsyncer.go:79-84)
- status upsync upstream via the status subresource, stale-RV conflicts
  requeue (statussyncer.go:41-63)
"""

from __future__ import annotations

import asyncio
import copy
import logging
import os
import time
from typing import Sequence

import numpy as np

from .. import obs
from ..apis.scheme import GVR
from ..client import Client, Informer
from ..ops.diff import (
    DECISION_CREATE,
    DECISION_DELETE,
    DECISION_UPDATE,
)
from ..ops.encode import BucketEncoder, BucketOverflow, pad_pow2
from ..reconciler.controller import BatchController
from ..store.selectors import LabelSelector, parse_selector
from ..utils import errors

log = logging.getLogger(__name__)

CLUSTER_LABEL = "kcp.dev/cluster"
OWNED_BY_LABEL = "kcp.dev/owned-by"

DEFAULT_RESYNC_PERIOD = 600.0  # the collision/missed-event safety net

# metadata fields that must not cross the cluster boundary
# (reference: specsyncer.go:97-108 strips UID + ResourceVersion and drops
# owner references pointing at the kcp-side owner)
_STRIP_META = ("uid", "resourceVersion", "creationTimestamp", "generation",
               "managedFields", "clusterName", "ownerReferences", "deletionTimestamp")


def transform_for_downstream(obj: dict) -> dict:
    out = copy.deepcopy(obj)
    out.pop("status", None)
    meta = out.get("metadata") or {}
    for f in _STRIP_META:
        meta.pop(f, None)
    return out


def _sync_view(obj: dict) -> dict:
    """The canonical comparable view of an object on either side.

    Both mirrors encode this view, so side-local fields (uid, RV, owner
    refs) can never make the lanes dirty.
    """
    view = transform_for_downstream(obj)
    if "status" in obj:
        view["status"] = copy.deepcopy(obj["status"])
    return view


def _sync_view_ro(obj: dict) -> dict:
    """:func:`_sync_view` without the deepcopy tax, for read-only
    consumers (the encoders hash it, `_spec_differs` compares it). The
    nested values stay shared with the informer caches — which the CoW
    store shares with storage — so callers must not mutate the result;
    write paths keep using the deep-copying :func:`_sync_view` /
    :func:`transform_for_downstream`."""
    out = {k: v for k, v in obj.items() if k != "status"}
    meta = out.get("metadata") or {}
    out["metadata"] = {k: v for k, v in meta.items() if k not in _STRIP_META}
    if "status" in obj:
        out["status"] = obj["status"]
    return out


class BatchSyncEngine:
    """One batched sync program for one GVR between two clusters.

    ``backend="tpu"`` registers a row section in the process-wide
    :class:`~kcp_tpu.syncer.core.FusedCore`: every engine's rows live in a
    shared schema bucket and each reconcile tick runs ONE fused
    ``reconcile_step_packed`` over the whole fleet — the same program
    ``bench.py`` measures. ``backend="host"`` computes identical decisions
    in pure Python per engine — the differential-testing reference
    (SURVEY.md §7.1).

    Applies are pipelined: the tick never waits on a store write. Patches
    go to an applier pool that verifies against the live caches, applies
    with optimistic concurrency, and retries with per-key backoff
    (5 retries then drop, RetryableError forever — reference parity with
    pkg/syncer/syncer.go:272-291).
    """

    def __init__(
        self,
        upstream: Client,
        downstream: Client,
        gvr: GVR | str,
        cluster_id: str,
        backend: str = "tpu",
        namespace_gvr: GVR | str = "namespaces",
        batch_window: float = 0.002,
        resync_period: float | None = DEFAULT_RESYNC_PERIOD,
        core=None,
        mesh=None,
        apply_workers: int = 4,
        max_apply_retries: int = 5,
        pipeline: str | None = None,
    ):
        self.upstream = upstream
        self.downstream = downstream
        self.gvr = gvr
        self.cluster_id = cluster_id
        self.backend = backend
        self.fused = backend == "tpu"
        self.core = core
        self.mesh = mesh  # sharding for the fused core (None = serving default)
        # tick pipelining for the fused core (None = KCP_PIPELINE env /
        # "double"); only consulted when this engine creates the core
        self.pipeline = pipeline
        self.namespace_gvr = namespace_gvr
        self.selector: LabelSelector = parse_selector(f"{CLUSTER_LABEL}={cluster_id}")

        self.up_informer = Informer(
            upstream, gvr, selector=self.selector, resync_period=resync_period
        )
        self.down_informer = Informer(
            downstream, gvr, selector=self.selector, resync_period=resync_period
        )

        self.enc = BucketEncoder(capacity=64)
        # encode-once memo for the _sync_view_ro encode path: the CoW
        # store (and the informer caches fed from it) never mutates a
        # snapshot in place, so the uint32 row for a snapshot is a pure
        # function of the dict — keyed by id with a strong ref (presence
        # implies identity), cleared whenever self.enc is replaced
        # (slot assignments are append-only below that, so cached rows
        # stay valid as the vocabulary grows). Periodic resyncs and
        # level-triggered re-touches of unchanged keys hit this instead
        # of re-flattening the object.
        self._enc_memo_on = os.environ.get(
            "KCP_ENCODE_CACHE", "1").lower() not in ("0", "false", "off")
        self._enc_memo: dict[int, tuple[dict, np.ndarray]] = {}
        self._enc_memo_max = 65536
        self.rows: dict[tuple[str, str], int] = {}  # (ns, name) -> row
        self.row_keys: list[tuple[str, str]] = []
        self.capacity = 0
        # host staging mirrors (host-backend state; fused mode stages into
        # the shared bucket instead)
        self.up_vals = self.up_exists = self.down_vals = self.down_exists = None

        self.controller = None
        self._section = None
        if not self.fused:
            self.controller = BatchController(
                f"sync-{cluster_id}-{gvr}", self._process_batch,
                batch_window=batch_window,
            )
        self.up_informer.add_handler(self._on_up_event)
        self.down_informer.add_handler(self._on_down_event)

        # pipelined applier pool
        self.apply_workers = apply_workers
        self.max_apply_retries = max_apply_retries
        self._apply_q: asyncio.Queue | None = None
        self._apply_pending: set = set()
        self._apply_failures: dict = {}  # key -> consecutive failure count
        self._apply_tasks: list[asyncio.Task] = []
        self._retry_tasks: set[asyncio.Task] = set()

        # convergence bookkeeping for the p99 metric: key -> first-dirty
        # time; samples are bounded (a long-running server must not grow
        # them forever — the histogram in utils/trace keeps the totals)
        from collections import deque

        self.dirty_since: dict[tuple[str, str], float] = {}
        self.convergence_samples: "deque[float]" = deque(maxlen=10_000)
        self.stats = {"ticks": 0, "decisions_applied": 0, "rows": 0, "full_uploads": 0}
        # convergence trace attribution (kcp_tpu/obs): key -> the traced
        # spec write's context + the phase-boundary timestamps gathered
        # as the row moves stage → tick → patch → downstream → upstatus.
        # Entries exist only for sampled writes (identity-linked
        # snapshots, or engine-minted fragments under always-on
        # sampling), bounded FIFO — the steady-state cost when tracing
        # is on but nothing is sampled is one dict-emptiness check.
        self._conv: dict[tuple[str, str], dict] = {}
        self._conv_max = 1024
        self._tick_bounds: tuple[float, float] | None = None

    def tick_count(self) -> int:
        """Reconcile ticks that covered this engine's rows (fused mode
        reports the shared bucket's tick counter)."""
        if self.fused and self._section is not None:
            return self._section.bucket.stats["ticks"]
        return self.stats["ticks"]

    # ------------------------------------------------------------ events

    @staticmethod
    def _obj_key(obj: dict) -> tuple[str, str]:
        m = obj["metadata"]
        return (m.get("namespace", ""), m["name"])

    def _on_up_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        key = self._obj_key(new or old)
        self.dirty_since.setdefault(key, time.monotonic())
        self._apply_failures.pop(key, None)  # new data resets the budget
        if new is not None and obs.TRACER.enabled and key not in self._conv:
            ctx = obs.conv_begin(new)
            if ctx is not None:
                while len(self._conv) >= self._conv_max:
                    self._conv.pop(next(iter(self._conv)))
                meta = new.get("metadata") or {}
                self._conv[key] = {
                    "ctx": ctx, "state": "staged", "t0": time.time(),
                    "rv": str(meta.get("resourceVersion", "")),
                    "name": meta.get("name", "")}
        if self.fused:
            if self._section is not None:
                self.core.enqueue(self._section, False, key)
        else:
            self.controller.enqueue(("up", key))

    def _on_down_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        key = self._obj_key(new or old)
        self._apply_failures.pop(key, None)
        if self._conv:
            # downstream churn (our own create echo, then the status
            # write) re-stages the row: remember the LAST arrival as the
            # downstream→upsync boundary (phases recorded at upsync)
            ent = self._conv.get(key)
            if ent is not None and ent["state"] in ("patched", "downstaged"):
                ent["t_down"] = time.time()
                ent["state"] = "downstaged"
        if self.fused:
            if self._section is not None:
                self.core.enqueue(self._section, True, key)
        else:
            self.controller.enqueue(("down", key))

    # ----------------------------------------------- fused-core interface

    def fused_status_mask(self) -> np.ndarray:
        return self.enc.status_mask()

    def fused_ledger_key(self) -> tuple[str, str]:
        """(cluster, resource) key for the fleet batch's device-side
        per-segment counters: the quota ledger's interning key
        (admission/quota.py ``ingest_device_counts``), so this engine's
        live synced rows are counted on-device every tick."""
        return (self._up_cluster(), str(self.gvr))

    def _encode_view(self, obj: dict) -> np.ndarray:
        """Encode-once ``enc.encode(_sync_view_ro(obj))``: memoized per
        snapshot identity. The returned row is shared — callers copy it
        into staging buffers, never mutate it."""
        if not self._enc_memo_on:
            return self.enc.encode(_sync_view_ro(obj))
        ent = self._enc_memo.get(id(obj))
        if ent is not None and ent[0] is obj:
            return ent[1]
        vec = self.enc.encode(_sync_view_ro(obj))
        if len(self._enc_memo) >= self._enc_memo_max:
            # blunt but bounded: informer caches churn snapshots, so a
            # periodic full reset beats per-entry tracking on this path
            self._enc_memo.clear()
        self._enc_memo[id(obj)] = (obj, vec)
        return vec

    def fused_encode(self, key: tuple[str, str]):
        """Re-encode one touched key from the informer caches for the
        shared bucket's scatter. Raises BucketOverflow if the vocabulary
        outgrew the bucket (the core then calls :meth:`fused_overflow`)."""
        ns, name = key
        up_obj = self.up_informer.get(self._up_cluster(), name, ns)
        down_obj = self.down_informer.get(self._down_cluster(), name, ns)
        s = self.enc.capacity
        up_v = (self._encode_view(up_obj) if up_obj is not None
                else np.zeros(s, np.uint32))
        down_v = (self._encode_view(down_obj) if down_obj is not None
                  else np.zeros(s, np.uint32))
        # converged-by-observation: both sides present and identical means
        # this key's churn has landed — close its convergence sample here
        # (actioned keys close theirs in the applier)
        if (up_obj is None) == (down_obj is None) and bool((up_v == down_v).all()):
            self._sample_convergence(key)
        return up_v, up_obj is not None, down_v, down_obj is not None

    def fused_apply(self, patches: list[tuple[tuple[str, str], int, bool]]) -> None:
        """Patch rows from a collected tick: feed the applier pool
        (dedup per key; the pool re-verifies against live caches)."""
        if self._conv and patches:
            # stamp which fused dispatch carried each traced row: the
            # core's wall-clock tick anchor + this collect time bound
            # the "tick" phase, and the bucket tick counter names it
            t1 = time.time()
            t0 = getattr(self.core, "last_tick_start", None) or t1
            tick_n = (self._section.bucket.stats.get("ticks")
                      if self._section is not None else None)
            for key, _code, _upsync in patches:
                ent = self._conv.get(key)
                if ent is not None and "tb" not in ent:
                    ent["tb"] = (t0, t1)
                    ent["tick"] = tick_n
        for key, code, upsync in patches:
            if key in self._apply_pending:
                continue
            if self._apply_failures.get(key, 0) > self.max_apply_retries:
                continue  # dropped until a new event resets the budget
            self._apply_pending.add(key)
            self._apply_q.put_nowait((key, code, upsync))

    def fused_overflow(self) -> None:
        """Vocabulary outgrew the bucket: grow the encoder (vocab is a
        prefix, so existing slot assignments stay valid), move to the
        larger bucket, and replay every cached key."""
        self.enc = self.enc.grown()
        self._enc_memo.clear()  # rows are sized to the replaced encoder
        log.info("sync-%s-%s: bucket overflow, re-registering at %d slots",
                 self.cluster_id, self.gvr, self.enc.capacity)
        old = self._section
        self._section = self.core.register(self, self.enc.capacity)
        if old is not None:
            old.release()
        self.core.enqueue_many(self._section, False, self._all_keys())

    def _all_keys(self) -> set:
        keys = {(k[1], k[2]) for k in self.up_informer.cache}
        keys |= {(k[1], k[2]) for k in self.down_informer.cache}
        return keys

    def _sample_convergence(self, key) -> None:
        started = self.dirty_since.pop(key, None)
        if started is not None:
            from ..utils.trace import REGISTRY

            dt = time.monotonic() - started
            self.convergence_samples.append(dt)
            REGISTRY.histogram("kcp_sync_convergence_seconds",
                               "spec churn to observed convergence").observe(dt)

    # ----------------------------------------------------- applier pool

    async def _apply_worker(self) -> None:
        while True:
            key, code, upsync = await self._apply_q.get()
            try:
                applied = await self._apply_async(key, code, upsync)
            except Exception as err:  # noqa: BLE001 — reconcile errors are data
                self._apply_failed(key, code, upsync, err)
            else:
                self._apply_failures.pop(key, None)
                if applied:
                    self.stats["decisions_applied"] += 1
            finally:
                # pending holds until the apply FINISHES: a slow apply
                # must suppress the level-triggered re-patches every tick
                # emits for its still-divergent row, or duplicates of one
                # slow key eat the whole worker pool. Anything that
                # changed mid-apply is recovered by the next tick — the
                # row is still divergent, pending is clear, it re-patches
                self._apply_pending.discard(key)
                self._apply_q.task_done()

    async def _apply_async(self, key, code: int, upsync: bool) -> bool:
        """Apply one verified decision. Override (or monkeypatch) to make
        applies genuinely asynchronous (e.g. thread-pooled REST calls) —
        the tick loop never waits on this. ``syncer.apply`` is a
        KCP_FAULTS injection point (error -> the worker's normal
        failure/backoff path; latency -> an awaited delay, so a slow
        apply exercises the pending-dedup discipline, never the tick)."""
        from .. import faults

        delay = faults.maybe_fail("syncer.apply")
        if delay:
            await asyncio.sleep(delay)
        return self._apply_decision(key, code, upsync)

    def _apply_failed(self, key, code: int, upsync: bool, err: Exception) -> None:
        n = self._apply_failures.get(key, 0) + 1
        self._apply_failures[key] = n  # backoff escalates for every failure
        retryable = errors.is_retryable(err)
        if not retryable and n > self.max_apply_retries:
            log.warning("sync-%s-%s: dropping %r after %d apply retries: %s",
                        self.cluster_id, self.gvr, key, n - 1, err)
            return
        delay = min(0.005 * (2 ** min(n, 10)), 5.0)
        hint = errors.retry_after_hint(err)
        if hint is not None:
            # 429 from an overloaded frontend: honor the server's pacing
            # hint (jittered so the applier pool doesn't re-arrive in
            # lockstep, capped so a bogus hint can't stall the row)
            import random

            delay = max(delay, min(hint, 30.0) * (1.0 + 0.25 * random.random()))
        log.info("sync-%s-%s: apply %r failed (attempt %d): %s",
                 self.cluster_id, self.gvr, key, n, err)
        t = asyncio.get_event_loop().create_task(
            self._retry_apply(key, code, upsync, delay))
        self._retry_tasks.add(t)
        t.add_done_callback(self._retry_tasks.discard)

    async def _retry_apply(self, key, code: int, upsync: bool, delay: float) -> None:
        await asyncio.sleep(delay)
        if key not in self._apply_pending:
            self._apply_pending.add(key)
            self._apply_q.put_nowait((key, code, upsync))

    # ------------------------------------------------------------- rows

    def _ensure_capacity(self, needed: int) -> None:
        if self.capacity >= needed and self.up_vals is not None:
            return
        new_cap = pad_pow2(max(needed, 8))
        s = self.enc.capacity

        def grow(a, shape, dtype):
            out = np.zeros(shape, dtype=dtype)
            if a is not None:
                src = np.asarray(a)
                out[: src.shape[0], ...] = src
            return out

        self.up_vals = grow(self.up_vals, (new_cap, s), np.uint32)
        self.down_vals = grow(self.down_vals, (new_cap, s), np.uint32)
        self.up_exists = grow(self.up_exists, (new_cap,), bool)
        self.down_exists = grow(self.down_exists, (new_cap,), bool)
        self.capacity = new_cap

    def _row_for(self, key: tuple[str, str]) -> int:
        row = self.rows.get(key)
        if row is None:
            row = len(self.row_keys)
            self.rows[key] = row
            self.row_keys.append(key)
            self._ensure_capacity(row + 1)
        return row

    def _rebuild_after_overflow(self) -> None:
        """Encoder outgrew its slots: grow until everything fits, then
        re-encode both caches (the host escape hatch for odd objects)."""
        while True:
            self.enc = self.enc.grown()
            self._enc_memo.clear()  # rows are sized to the replaced encoder
            log.info("%s: bucket overflow, re-encoding at %d slots",
                     self.controller.name, self.enc.capacity)
            cap = self.capacity
            s = self.enc.capacity
            self.up_vals = np.zeros((cap, s), np.uint32)
            self.down_vals = np.zeros((cap, s), np.uint32)
            self.up_exists = np.zeros(cap, bool)
            self.down_exists = np.zeros(cap, bool)
            try:
                for (_cl, ns, name), obj in self.up_informer.cache.items():
                    r = self._row_for((ns, name))
                    self.enc.encode(_sync_view_ro(obj), out=self.up_vals[r])
                    self.up_exists[r] = True
                for (_cl, ns, name), obj in self.down_informer.cache.items():
                    r = self._row_for((ns, name))
                    self.enc.encode(_sync_view_ro(obj), out=self.down_vals[r])
                    self.down_exists[r] = True
                break
            except BucketOverflow:
                continue

    # -------------------------------------------------------------- tick

    async def _process_batch(self, items: Sequence) -> list[tuple[object, Exception]]:
        from ..utils.trace import span

        with span("kcp_sync_tick"):
            return await self._process_batch_timed(items)

    async def _process_batch_timed(self, items: Sequence) -> list[tuple[object, Exception]]:
        from ..utils.trace import REGISTRY

        self.stats["ticks"] += 1
        t_tick0 = time.time()
        REGISTRY.counter("kcp_sync_ticks_total",
                         "reconcile ticks across all sync sessions").inc()
        REGISTRY.counter("kcp_sync_events_total",
                         "informer events drained into tick batches").inc(len(items))
        # 1. dedup keys touched this tick (last event wins — we re-read
        #    caches), remembering which queue items map to each key so
        #    failures are charged to the right items' retry budgets
        key_items: dict[tuple[str, str], list] = {}
        for item in items:
            key_items.setdefault(item[1], []).append(item)

        # 2. re-encode touched keys from the informer caches
        try:
            deltas = self._apply_touched(key_items.keys())
        except BucketOverflow:
            self._rebuild_after_overflow()
            deltas = None

        # 3. full-mirror diff (pure-host reference backend; the tpu
        #    backend runs through the FusedCore, not this path)
        del deltas
        n = len(self.row_keys)
        if n == 0:
            return []
        decision, upsync = self._host_decisions()
        # wall-clock tick bounds for convergence attribution (the host
        # backend's analog of the fused core's last_tick_start)
        self._tick_bounds = (t_tick0, time.time())

        # 4. apply non-NOOP rows with host verification
        failed_keys: dict[tuple[str, str], Exception] = {}
        act_rows = np.nonzero((decision != 0) | upsync)[0]
        for r in act_rows:
            if r >= n:
                continue
            key = self.row_keys[r]
            try:
                applied = self._apply_decision(key, int(decision[r]), bool(upsync[r]))
                if applied:
                    self.stats["decisions_applied"] += 1
            except Exception as err:  # noqa: BLE001 — reconcile errors are data
                failed_keys[key] = err

        # touched keys that needed no action converged by observation
        act_set = {self.row_keys[r] for r in act_rows if r < n}
        now = time.monotonic()
        conv_h = REGISTRY.histogram("kcp_sync_convergence_seconds",
                                    "spec churn to observed convergence")
        for key in key_items:
            if key not in act_set:
                started = self.dirty_since.pop(key, None)
                if started is not None:
                    self.convergence_samples.append(now - started)
                    conv_h.observe(now - started)
        self.stats["rows"] = n

        # failures on rows whose items are in this batch charge those
        # items; failed rows woken by *earlier* batches already have a
        # backing-off item in the queue and will be retried by it
        failed: list[tuple[object, Exception]] = []
        for key, err in failed_keys.items():
            for item in key_items.get(key, ()):
                failed.append((item, err))
        return failed

    def _apply_touched(self, keys):
        """Refresh host mirrors for the touched keys; return the delta batch
        (idx, up_rows, up_ex, down_rows, down_ex) for the device scatter."""
        idxs, up_rows, up_ex, down_rows, down_ex = [], [], [], [], []
        for key in keys:
            r = self._row_for(key)
            ns, name = key
            up_obj = self.up_informer.get(self._up_cluster(), name, ns)
            down_obj = self.down_informer.get(self._down_cluster(), name, ns)
            idxs.append(r)
            up_rows.append(
                self._encode_view(up_obj) if up_obj is not None
                else np.zeros(self.enc.capacity, np.uint32)
            )
            up_ex.append(up_obj is not None)
            down_rows.append(
                self._encode_view(down_obj) if down_obj is not None
                else np.zeros(self.enc.capacity, np.uint32)
            )
            down_ex.append(down_obj is not None)
        if not idxs:
            return None
        for i, r in enumerate(idxs):
            self.up_vals[r] = up_rows[i]
            self.up_exists[r] = up_ex[i]
            self.down_vals[r] = down_rows[i]
            self.down_exists[r] = down_ex[i]
        return (
            np.array(idxs, np.int32),
            np.stack(up_rows),
            np.array(up_ex, bool),
            np.stack(down_rows),
            np.array(down_ex, bool),
        )

    # ---------------------------------------------------------- backends

    def _host_decisions(self) -> tuple[np.ndarray, np.ndarray]:
        """Pure-python decision oracle (Backend=host)."""
        n = self.capacity
        decision = np.zeros(n, np.uint8)
        upsync = np.zeros(n, bool)
        status_mask = self.enc.status_mask()
        for r in range(len(self.row_keys)):
            ue, de = self.up_exists[r], self.down_exists[r]
            neq = self.up_vals[r] != self.down_vals[r]
            spec_dirty = bool((neq & ~status_mask).any())
            status_dirty = bool((neq & status_mask).any())
            if ue and not de:
                decision[r] = DECISION_CREATE
            elif de and not ue:
                decision[r] = DECISION_DELETE
            elif ue and de and spec_dirty:
                decision[r] = DECISION_UPDATE
            upsync[r] = ue and de and status_dirty
        return decision, upsync

    def _up_cluster(self) -> str:
        return self.up_informer.client.cluster

    def _down_cluster(self) -> str:
        return self.down_informer.client.cluster

    # ------------------------------------------------------------- apply

    def _conv_phases_pre(self, ent: dict) -> None:
        """Record the stage + tick phases of a traced row the first time
        an actionable decision reaches the applier: staged→tick-start is
        queue wait, tick-start→tick-end is the dispatch that carried the
        row (fused: the core's wall anchor; host: the batch bounds)."""
        tb = ent.get("tb") or self._tick_bounds or (ent["t0"], ent["t0"])
        t0 = max(ent["t0"], min(tb[0], tb[1]))
        ctx = ent["ctx"]
        obs.phase("stage", ctx, ent["t0"], t0, rv=ent["rv"],
                  obj=ent["name"])
        obs.phase("tick", ctx, t0, max(t0, tb[1]), rv=ent["rv"],
                  tick=ent.get("tick"))
        ent["state"] = "ticked"
        ent["t_tick1"] = max(t0, tb[1])

    def _apply_decision(self, key: tuple[str, str], decision: int, upsync: bool) -> bool:
        ns, name = key
        up_obj = self.up_informer.get(self._up_cluster(), name, ns)
        down_obj = self.down_informer.get(self._down_cluster(), name, ns)
        applied = False
        ent = self._conv.get(key) if self._conv else None
        if ent is not None and ent["state"] == "staged" and decision:
            self._conv_phases_pre(ent)

        if decision == DECISION_CREATE and up_obj is not None:
            self._ensure_namespace(ns)
            desired = transform_for_downstream(up_obj)
            try:
                self.downstream.create(self.gvr, desired, namespace=ns)
                applied = True
            except errors.AlreadyExistsError:
                # informer lag: fall through to update semantics
                current = self.downstream.get(self.gvr, name, ns)
                if self._spec_differs(desired, current):
                    merged = self._merged_downstream(desired, current)
                    self.downstream.update(self.gvr, merged, namespace=ns)
                    applied = True
        elif decision == DECISION_UPDATE and up_obj is not None and down_obj is not None:
            desired = transform_for_downstream(up_obj)
            # host verification: never trust a hash alone before writing
            if self._spec_differs(desired, down_obj):
                current = self.downstream.get(self.gvr, name, ns)
                merged = self._merged_downstream(desired, current)
                self.downstream.update(self.gvr, merged, namespace=ns)
                applied = True
        elif decision == DECISION_DELETE and down_obj is not None and up_obj is None:
            # the up_obj re-check re-derives the action at apply time: a
            # pipelined DELETE must not fire if the object reappeared
            # upstream while the patch was in flight
            try:
                self.downstream.delete(self.gvr, name, ns)
                applied = True
            except errors.NotFoundError:
                pass

        if ent is not None and ent["state"] == "ticked":
            # the downstream write (or delete) for this traced row just
            # applied: tick-end → now is the patch phase
            now = time.time()
            obs.phase("patch", ent["ctx"], ent["t_tick1"], now,
                      rv=ent["rv"], applied=applied)
            ent["state"] = "patched"
            ent["t_patch"] = now

        if upsync and up_obj is not None and down_obj is not None:
            new_status = down_obj.get("status")
            if new_status != up_obj.get("status"):
                fresh = self.upstream.get(self.gvr, name, ns)
                fresh["status"] = copy.deepcopy(new_status)
                with obs.use(ent["ctx"] if ent is not None else None):
                    # upstream status write runs under the row's trace
                    # context: an in-process upstream records its
                    # store.commit as a child; a REST upstream carries
                    # the traceparent to the owning shard
                    self.upstream.update_status(self.gvr, fresh,
                                                namespace=ns)
                applied = True
                if ent is not None and ent["state"] in ("patched",
                                                        "downstaged"):
                    now = time.time()
                    t_patch = ent.get("t_patch", ent["t0"])
                    t_down = ent.get("t_down", t_patch)
                    obs.phase("downstream", ent["ctx"], t_patch, t_down,
                              rv=ent["rv"])
                    obs.phase("upstatus", ent["ctx"], t_down, now,
                              rv=ent["rv"], obj=ent["name"])
                    self._conv.pop(key, None)

        if applied or decision or upsync:
            started = self.dirty_since.pop(key, None)
            if started is not None:
                self.convergence_samples.append(time.monotonic() - started)
        return applied

    def _ensure_namespace(self, ns: str) -> None:
        if not ns:
            return
        try:
            self.downstream.get(self.namespace_gvr, ns)
        except errors.NotFoundError:
            try:
                self.downstream.create(
                    self.namespace_gvr,
                    {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}},
                )
            except errors.AlreadyExistsError:
                pass

    @staticmethod
    def _spec_differs(desired: dict, current: dict) -> bool:
        # pure comparison: the copy-free views suffice (and with informer
        # caches sharing CoW store snapshots, skipping the deepcopy here
        # keeps host verification off the per-patch allocation budget)
        return _sync_view_ro(desired) != {
            k: v for k, v in _sync_view_ro(current).items() if k != "status"
        }

    @staticmethod
    def _merged_downstream(desired: dict, current: dict) -> dict:
        merged = copy.deepcopy(desired)
        merged.setdefault("metadata", {})["resourceVersion"] = current["metadata"][
            "resourceVersion"
        ]
        return merged

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._apply_q = asyncio.Queue()
        for _ in range(self.apply_workers):
            self._apply_tasks.append(asyncio.create_task(self._apply_worker()))
        if self.fused:
            if self.core is None:
                from .core import FusedCore

                self.core = FusedCore.for_current_loop(
                    mesh=self.mesh, pipeline=self.pipeline)
            self._section = self.core.register(self, self.enc.capacity)
            await self.core.start()
        # informers after the section exists: their initial list replays
        # the cache through the handlers, which enqueue into the core
        await self.up_informer.start()
        await self.down_informer.start()
        if self.controller is not None:
            await self.controller.start()

    async def stop(self) -> None:
        if self.controller is not None:
            await self.controller.stop()
        if self.fused and self.core is not None:
            await self.core.stop()
            if self._section is not None:
                self._section.release()
                self._section = None
        # the core's shutdown drain may have enqueued final patches —
        # let the workers finish them before cancelling
        if self._apply_q is not None:
            try:
                await asyncio.wait_for(self._apply_q.join(), timeout=5.0)
            except asyncio.TimeoutError:
                log.warning("sync-%s-%s: applier queue not drained at stop",
                            self.cluster_id, self.gvr)
        for t in [*self._apply_tasks, *self._retry_tasks]:
            t.cancel()
        for t in [*self._apply_tasks, *self._retry_tasks]:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._apply_tasks.clear()
        self._retry_tasks.clear()
        await self.up_informer.stop()
        await self.down_informer.stop()
