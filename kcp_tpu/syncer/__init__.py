from .engine import BatchSyncEngine, transform_for_downstream
from .syncer import Syncer, start_syncer

__all__ = ["BatchSyncEngine", "Syncer", "start_syncer", "transform_for_downstream"]
