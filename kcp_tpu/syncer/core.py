"""FusedCore — the served control plane runs the flagship device program.

The reference runs one goroutine pair per (cluster, GVR)
(pkg/syncer/syncer.go:46-64 StartSyncer); round 1 of this build ran one
small device program per (cluster, GVR). This module closes the gap
between the benched program and the served one: every sync engine in the
process registers a row *section* inside a shared schema bucket, and each
reconcile tick runs ONE fused ``reconcile_step_packed`` per bucket —
resident donated state, packed one-array-each-way wire format, pipelined
collection — exactly the artifact ``bench.py`` measures.

Topology:

  FusedCore ── one per asyncio loop (the process's serving loop)
    ├── BatchController      one tick loop draining all engines' events
    └── FusedBucket(S)       one per slot capacity (the schema bucket)
          ├── ReconcileState device-resident [B, S] mirrors + per-row
          │                  status masks (engines have different slot
          │                  vocabularies, so masks are [B, S])
          └── Section        one per engine: a set of rows + callbacks

Tick pipeline — three explicit stages with a PIPELINE_DEPTH-deep
in-flight window (pipeline="double", the default; "serial" runs the
stages back-to-back as the A/B reference):

  drain/pack  — drain events (the NEXT batch drains concurrently with
                this tick: BatchController overlap_drain), engines
                encode touched keys, bucket stages rows into one of two
                rotating pre-allocated wire buffers (WireBuffers — tick
                N's device_put never races tick N+1's packing)
  dispatch    — device_put + fused step (donated resident state) +
                wire.copy_to_host_async(); the host never blocks here
  fetch/apply — wires beyond the in-flight window (2 ticks old, or any
                age via the idle flusher) are fetched — blocking ONLY on
                the compact patch wire, never the donated state — then
                unpacked and routed to owning sections; engines'
                appliers take it from there without blocking the tick

Patch overflow: the wire carries at most ``patch_capacity`` actionable
rows. Because the loop is level-triggered (every tick re-decides every
row), overflow loses nothing — the core doubles capacity (one recompile)
and re-ticks.

Mesh serving: pass ``mesh=`` to shard every bucket's state over a
(tenants, slots) device mesh — same layout as ``parallel/mesh.py`` and
``dryrun_multichip``. Stats reductions lower to cross-device collectives;
the packed wire batch is replicated (it is O(events), not O(fleet)).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Callable, NamedTuple, Protocol, Sequence

import jax
import numpy as np

from .. import faults
from ..models.reconcile_model import (
    MASK_STAMP_BIT,
    PACK_HDR,
    SEG_NONE,
    SEG_SHIFT,
    ReconcileState,
    WireBuffers,
    reconcile_step_fleet,
    reconcile_step_packed,
    unpack_patches,
    unpack_placement,
    unpack_seg_counts,
)
from ..ops.encode import pad_pow2
from ..reconciler.controller import BatchController
from ..utils.trace import DEPTH_BUCKETS, REGISTRY

log = logging.getLogger(__name__)


def _grown(a: np.ndarray, shape, dtype) -> np.ndarray:
    """Zero-padded copy of ``a`` at a larger ``shape`` (growth helper for
    the mirror and staging buffers)."""
    out = np.zeros(shape, dtype)
    out[: a.shape[0], ...] = a
    return out


def _resolve_donate() -> bool:
    """Per-backend state-donation policy (shared by FusedBucket and
    FleetBatch): donation is the design on accelerators (steady state
    lives in HBM), but the CPU pjrt client (jaxlib 0.4.36) mishandles it
    under the pipelined window — see FusedBucket.__init__. KCP_DONATE=0/1
    overrides the backend default."""
    env_donate = os.environ.get("KCP_DONATE", "")
    if env_donate in ("0", "1"):
        return env_donate == "1"
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — backend init failure
        return False


def _phase(name: str, dt: float) -> None:
    """Record one tick-phase timing (histogram ``fused_<name>_seconds``).

    The point-sample form of :func:`kcp_tpu.utils.trace.span` — used here
    because the tick segments (pack/put/step) share perf_counter points
    across branches and a with-block per segment cannot express that, and
    because some phases must record only on qualifying ticks (encode only
    when keys were touched) to keep the means meaningful. Same registry,
    same naming convention as span.

    The per-phase breakdown is the 'where does tick time go' answer the
    /debug/profile surface and bench.py report; keep observations cheap —
    one perf_counter pair per phase per tick, never per row."""
    REGISTRY.histogram(f"fused_{name}_seconds").observe(dt)

MIN_ROWS = 64
MIN_EVENTS = 64
MIN_PATCH_CAPACITY = 256
# pipelined tick window: in-flight steps per bucket before a blocking
# collect. Depth 2 is the double-buffered pipeline — while the device
# executes tick N, the host packs tick N+1 and applies tick N-1 — and
# matches WireBuffers' two staging slots (a deeper window would reuse a
# staging buffer while its transfer could still be in flight). "serial"
# mode (depth 0) is the A/B reference: pack -> step -> fetch -> apply
# with no overlap, the sum-of-phases loop the pipeline exists to beat.
PIPELINE_DEPTH = 2
PIPELINE_MODES = ("serial", "double")
IDLE_FLUSH_S = 0.003  # collect leftovers when no new tick arrives
# poison-row quarantine: a failed device step is retried once wholesale
# (full re-upload from the host mirrors); a second consecutive failure
# bisects the submitted rows with probe steps to isolate the poison.
# Quarantined keys are requeued to their owners with bounded backoff.
QUARANTINE_BASE_BACKOFF = 0.05
QUARANTINE_MAX_BACKOFF = 5.0
BISECT_MAX_PROBES = 64


def _group_test_poison(probe: Callable[[Sequence[int]], bool],
                       groups: Sequence[Sequence[int]],
                       max_probes: int) -> list[int]:
    """The shared bisection loop: group-test ``groups`` of suspect rows
    against a probe oracle (~k*log2(n) probes for k poisons). Seeding
    with one group per segment makes the fleet bisection segment-scoped:
    a clean segment is cleared in ONE probe, and poison isolates within
    its own segment without probing cross-segment mixtures."""
    bad: list[int] = []
    stack: list[list[int]] = [list(g) for g in groups if g]
    probes = 0
    while stack:
        rows = stack.pop()
        if not rows:
            continue
        if probes >= max_probes:
            log.warning("fused-core: bisection probe budget exhausted; "
                        "quarantining %d unresolved rows wholesale",
                        len(rows))
            bad.extend(rows)
            continue
        probes += 1
        if probe(rows):
            continue
        if len(rows) == 1:
            bad.append(rows[0])
        else:
            mid = len(rows) // 2
            stack.append(rows[:mid])
            stack.append(rows[mid:])
    return bad


class SectionOwner(Protocol):
    """What an engine provides to its section (see BatchSyncEngine)."""

    def fused_encode(self, key) -> tuple[np.ndarray, bool, np.ndarray, bool]:
        """(up_vals[S], up_exists, down_vals[S], down_exists) for a key,
        re-read from the informer caches. May raise BucketOverflow."""
        ...

    def fused_status_mask(self) -> np.ndarray:
        """bool[S] — the engine's current status-slot mask."""
        ...

    def fused_apply(self, patches: list[tuple[object, int, bool]]) -> None:
        """Receive (key, decision_code, upsync) patches for this engine's
        rows. Must not block the loop (hand off to an applier pool)."""
        ...

    def fused_overflow(self) -> None:
        """The engine's slot vocabulary outgrew its bucket: grow the
        encoder, re-register in a larger bucket, replay all rows."""
        ...


class Section:
    """One engine's row allocation inside a bucket."""

    def __init__(self, bucket: "FusedBucket", owner: SectionOwner):
        self.bucket = bucket
        self.owner = owner
        self.rows: dict[object, int] = {}  # key -> global row
        self.row_keys: dict[int, object] = {}  # global row -> key
        # seed the mask cache now: row_for stamps every new row with the
        # current mask, so refresh_mask must only fire on real changes
        self._mask: np.ndarray = owner.fused_status_mask().copy()
        # fleet segment id (FusedCore.register assigns it): the per-row
        # identity the ragged fleet batch carries on device so the
        # per-segment counters can attribute live rows to this section
        self.seg: int | None = None
        self.released = False

    def row_for(self, key) -> int:
        row = self.rows.get(key)
        if row is None:
            row = self.bucket.alloc_row(self)
            self.rows[key] = row
            self.row_keys[row] = key
            # stamp with the cached mask; refresh_mask restamps everything
            # if the owner's vocabulary has drifted since
            self.bucket.status_mask[row, : self._mask.shape[0]] = self._mask
            # the DEVICE must see this stamp too: the delta wire carries
            # values only, and without a mask stamp a row allocated after
            # the last full upload reads its status churn as spec churn
            # forever (fuzz-found) — ship it as a wire entry. A stale
            # bucket needs no stamp: the pending full upload carries the
            # host mask arrays wholesale (and bulk row preallocation
            # before the first tick would otherwise stage one per row)
            # in fleet mode EVERY new row stamps (even an all-False mask):
            # the stamp entry is also how the device learns the row's
            # segment id for the per-segment counters
            if ((self._mask.any() or self.bucket.always_stamp)
                    and not self.bucket._stale):
                self.bucket.stage_mask(row, self.bucket.status_mask[row])
        return row

    def refresh_mask(self) -> None:
        """Restamp this section's rows after the owner's vocabulary grew
        new status slots (rare; triggers a full re-upload)."""
        mask = self.owner.fused_status_mask()
        if np.array_equal(self._mask, mask):
            return
        self._mask = mask.copy()
        for row in self.rows.values():
            self.bucket.status_mask[row] = False
            self.bucket.status_mask[row, : mask.shape[0]] = mask
        self.bucket.mark_stale()

    def release(self) -> None:
        self.released = True
        for row in self.rows.values():
            self.bucket.free_row(row)
        self.rows.clear()
        self.row_keys.clear()


class FusedBucket:
    """One schema bucket: host staging + device-resident fused state."""

    def __init__(self, slots: int, mesh=None, use_pallas: bool = False,
                 always_stamp: bool = False):
        self.S = slots
        self.B = 0
        self.mesh = mesh
        # the fused Pallas decision+fanout pass (ops/pallas_kernels.py);
        # on a mesh it runs per device via shard_map (reconcile_model
        # gates on local-row divisibility and falls back to XLA lanes)
        self.use_pallas = use_pallas
        # fleet mode: every newly-allocated row stages a mask stamp (the
        # wire entry that also carries its segment id), mask or no mask
        self.always_stamp = always_stamp
        # converged-row ack compression kill switch, resolved once (the
        # opt-out cannot change mid-process; staging is the hot path)
        self.use_acks = os.environ.get("KCP_NO_ACKS") != "1"
        # sharded state must device_put cleanly: row counts are padded to
        # a multiple of the row-axis product (see _grow), and the slots
        # axis must divide the (power-of-two) slot capacity up front
        self._row_factor = 1
        if mesh is not None:
            from ..parallel.mesh import row_factor, slot_factor

            self._row_factor = row_factor(mesh)
            slot_dim = slot_factor(mesh)
            if slots % slot_dim:
                raise ValueError(
                    f"bucket slot capacity {slots} is not divisible by the "
                    f"mesh slots axis ({slot_dim}); use a power-of-two "
                    f"slots axis"
                )
        self.up_vals = np.zeros((0, slots), np.uint32)
        self.down_vals = np.zeros((0, slots), np.uint32)
        self.up_exists = np.zeros(0, bool)
        self.down_exists = np.zeros(0, bool)
        self.status_mask = np.zeros((0, slots), bool)
        self.sections: list[Section] = []
        self.row_owner: dict[int, Section] = {}
        self._free: list[int] = []
        self._next = 0
        # placement lanes (the deployment splitter's serving section):
        # root rows with replicas + per-cluster availability, returned as
        # compacted dirty rows in the wire's placement segment
        self.placement_owner = None
        self.P = 8
        self.R = 0
        self.pl_replicas = np.zeros(0, np.int32)
        self.pl_avail = np.zeros((0, 8), bool)
        self.pl_rows: dict[object, int] = {}
        self.pl_row_keys: dict[int, object] = {}
        self._pl_free: list[int] = []
        self._pl_next = 0
        self._pl_staged = False
        self._state: ReconcileState | None = None
        self._stale = True
        self.patch_capacity = MIN_PATCH_CAPACITY
        # staged events for the next tick, accumulated directly in the
        # packed-wire layout (vals / row / flags) with last-wins dedup via
        # an O(1) (row<<1|side) -> slot map. The dict-of-arrays this
        # replaced cost ~23ms/tick at bench scale (encode staging + the
        # np.stack repack); the array form stages and packs in ~2ms.
        self._staged_slot = np.full(0, -1, np.int32)  # [2B] key -> slot
        self._staged_vals = np.zeros((0, slots), np.uint32)
        self._staged_rows = np.zeros(0, np.uint32)
        self._staged_flags = np.zeros(0, np.uint32)
        self._staged_keys = np.zeros(0, np.int64)  # slot -> key, for reset
        # converged-row ack compression (reconcile_step_packed's acks
        # lane): a down-side event equal to the resident up mirror ships
        # as a 4-byte row index instead of an (S+2)-column entry
        self._staged_ack = np.zeros(0, bool)
        self._staged_n = 0
        # mask stamps for rows allocated since the last full upload
        # (row -> bool[S]); ride the packed wire as MASK_STAMP entries
        self._staged_masks: dict[int, np.ndarray] = {}
        # acks-lane wire capacity: sticky high-water doubling, so the
        # (packed, acks) shape pair stays stable after warmup — per-tick
        # pow2 padding here would multiply compiled-shape variants. The
        # floor is generous (4 KB of -1s) because a mid-serving growth
        # costs a recompile — seconds of p99 — while padding costs ~µs
        self.ack_capacity = 1024
        # double-buffered packed-wire staging (models/reconcile_model.py):
        # tick N+1 packs into the other buffer while tick N's device_put
        # may still be reading this one — the allocation-free hot path
        # that makes the 2-deep pipeline window safe
        self._wire_bufs = WireBuffers(PIPELINE_DEPTH)
        # state donation is per-backend: on accelerators the donated
        # resident state is the design (steady state lives in HBM, only
        # deltas cross the link). The CPU pjrt client (jaxlib 0.4.36)
        # however mishandles donation under the pipelined window — an
        # output wire held across subsequent donated steps hits a
        # use-after-free (fuzz-reproducible segfault at depth 2, rare
        # flake at depth 1: outputs alias donated input buffers and the
        # client's aliasing bookkeeping breaks once >1 step chains
        # through them). On CPU donation only saves allocator churn (no
        # HBM, outputs are written wholesale either way), so correctness
        # wins. KCP_DONATE=0/1 overrides the backend default.
        self.donate = _resolve_donate()
        self._step = jax.jit(
            reconcile_step_packed,
            donate_argnums=(0,) if self.donate else (),
            static_argnames=("patch_capacity", "use_pallas", "mesh"),
        )
        # degraded-mode bookkeeping (poison-row quarantine): the rows the
        # last submission covered (the bisection's suspect set), the
        # consecutive step-failure count, and the non-donating probe step
        # used by the bisection (donation would consume the resident
        # state probes must leave intact)
        self._last_rows: list[int] = []
        self._step_failures = 0
        self._probe_step = None
        self._dropped_logged: set[int] = set()
        self.stats = {"ticks": 0, "full_uploads": 0, "overflows": 0,
                      "acked": 0, "step_failures": 0, "quarantined": 0}

    # ------------------------------------------------------------- rows

    def section(self, owner: SectionOwner) -> Section:
        s = Section(self, owner)
        self.sections.append(s)
        return s

    def alloc_row(self, section: Section) -> int:
        if self._free:
            row = self._free.pop()
        else:
            if self._next >= self.B:
                self._grow(self._next + 1)
            row = self._next
            self._next += 1
        self.row_owner[row] = section
        return row

    def free_row(self, row: int) -> None:
        self.up_exists[row] = self.down_exists[row] = False
        self.up_vals[row] = self.down_vals[row] = 0
        self.row_owner.pop(row, None)
        self._free.append(row)
        self.mark_stale()

    def _grow(self, needed: int) -> None:
        new_b = pad_pow2(max(needed, MIN_ROWS))
        if new_b % self._row_factor:
            # non-power-of-two row sharding (e.g. a 5-device tenants
            # axis): round up so every row dimension device_puts cleanly
            new_b += self._row_factor - new_b % self._row_factor

        self.up_vals = _grown(self.up_vals, (new_b, self.S), np.uint32)
        self.down_vals = _grown(self.down_vals, (new_b, self.S), np.uint32)
        self.up_exists = _grown(self.up_exists, (new_b,), bool)
        self.down_exists = _grown(self.down_exists, (new_b,), bool)
        self.status_mask = _grown(self.status_mask, (new_b, self.S), bool)
        slot = np.full(2 * new_b, -1, np.int32)
        slot[: self._staged_slot.shape[0]] = self._staged_slot
        self._staged_slot = slot
        self.B = new_b
        self.mark_stale()

    def mark_stale(self) -> None:
        self._stale = True

    # -------------------------------------------------------- placement

    def register_placement(self, owner, p: int = 8) -> None:
        """Attach the deployment splitter as this bucket's placement
        owner: its roots ride the replicas/avail lanes of the SAME fused
        step that serves the sync sections (VERDICT r3 item 5 — the
        serving tick computes real placement, not zeros)."""
        if self.placement_owner is not None and self.placement_owner is not owner:
            raise RuntimeError("bucket already has a placement owner")
        self.placement_owner = owner
        self.P = pad_pow2(max(p, 1), floor=8)
        if self.pl_avail.shape[1] != self.P:
            old = self.pl_avail
            self.pl_avail = np.zeros((old.shape[0], self.P), bool)
            self.pl_avail[:, : old.shape[1]] = old[:, : self.P]
            self.mark_stale()

    def pl_row_for(self, key) -> int:
        row = self.pl_rows.get(key)
        if row is None:
            if self._pl_free:
                row = self._pl_free.pop()
            else:
                if self._pl_next >= self.R:
                    self._pl_grow(self._pl_next + 1)
                row = self._pl_next
                self._pl_next += 1
            self.pl_rows[key] = row
            self.pl_row_keys[row] = key
        return row

    def free_pl_row(self, key) -> None:
        row = self.pl_rows.pop(key, None)
        if row is None:
            return
        self.pl_row_keys.pop(row, None)
        self.pl_replicas[row] = 0
        self.pl_avail[row] = False
        self._pl_free.append(row)
        # the device-resident `current` still holds this row's last split;
        # a future occupant staging inputs whose split EQUALS it would
        # never re-dirty — rebuild the resident state (root retirement is
        # rare relative to ticks, so the full upload is acceptable)
        self.mark_stale()

    def invalidate_placement(self) -> None:
        """Force every placement row to re-emit on the next tick (rebuilds
        the resident state, zeroing `current`). Used when a host-side
        apply rejected device counts — identical re-staged inputs would
        otherwise never re-dirty."""
        self.mark_stale()

    def _pl_grow(self, needed: int) -> None:
        new_r = pad_pow2(max(needed, 8))
        if new_r % self._row_factor:
            new_r += self._row_factor - new_r % self._row_factor
        self.pl_replicas = _grown(self.pl_replicas, (new_r,), np.int32)
        self.pl_avail = _grown(self.pl_avail, (new_r, self.P), bool)
        self.R = new_r
        # shape change: the resident current[R,P] must be rebuilt too
        self.mark_stale()

    def stage_placement(self, key, replicas: int, n_clusters: int) -> None:
        """Stage one root's desired placement inputs (replicas + how many
        of the P cluster slots are available). The width grows on demand
        — P is a padding floor, never a silent cap (matching the host
        splitter's 'width follows the widest row' contract)."""
        row = self.pl_row_for(key)
        if n_clusters > self.P:
            self._pl_widen(pad_pow2(n_clusters, floor=8))
        self.pl_replicas[row] = replicas
        self.pl_avail[row] = False
        self.pl_avail[row, :n_clusters] = True
        self._pl_staged = True

    def _pl_widen(self, new_p: int) -> None:
        avail = np.zeros((self.R, new_p), bool)
        avail[:, : self.P] = self.pl_avail
        self.pl_avail = avail
        self.P = new_p
        # shape change: resident avail/current must be rebuilt
        self.mark_stale()

    # ------------------------------------------------------------ events

    def _ensure_staged_capacity(self, need: int) -> None:
        cap = self._staged_vals.shape[0]
        if need <= cap:
            return
        new_cap = pad_pow2(max(need, MIN_EVENTS))
        self._staged_vals = _grown(self._staged_vals, (new_cap, self.S), np.uint32)
        self._staged_rows = _grown(self._staged_rows, (new_cap,), np.uint32)
        self._staged_flags = _grown(self._staged_flags, (new_cap,), np.uint32)
        self._staged_keys = _grown(self._staged_keys, (new_cap,), np.int64)
        self._staged_ack = _grown(self._staged_ack, (new_cap,), bool)

    def _clear_staged(self) -> None:
        n = self._staged_n
        if n:
            self._staged_slot[self._staged_keys[:n]] = -1
            self._staged_n = 0
        self._staged_masks.clear()

    def stage_mask(self, row: int, mask: np.ndarray) -> None:
        """Stage a status-mask stamp for a newly-allocated row (ships as
        a MASK_STAMP wire entry; a full upload supersedes it)."""
        self._staged_masks[row] = mask.copy()

    def stage(self, row: int, side: bool, vals: np.ndarray, exists: bool) -> None:
        """Stage one delta event (last-wins per (row, side)) and mirror it
        into host staging (the rebuild source of truth). The 1-row form
        of :meth:`stage_many` — one copy of the slot-map logic."""
        self.stage_many(np.array([row]), side, np.asarray(vals)[None, :],
                        np.array([exists]))

    def stage_many(self, rows: np.ndarray, side: bool, vals: np.ndarray,
                   exists: np.ndarray) -> None:
        """Vectorized :meth:`stage` for one side of a unique row batch
        (the fused_encode_many path): fancy-indexed mirror writes plus a
        single slot-map pass, no per-event python loop."""
        n, w = vals.shape
        ack_ok = None
        if side:
            if self.use_acks:
                # ack eligibility must be proven BEFORE any buffers
                # change: the event's value equals the host up mirror
                # (which equals the device's resident row, because no
                # up-side entry is staged for it this tick) — then the
                # device can produce the row itself from a 4-byte index
                ack_ok = (exists & self.up_exists[rows]
                          & (self._staged_slot[rows.astype(np.int64) << 1] < 0)
                          & (vals == self.up_vals[rows, :w]).all(axis=1))
                if w < self.S:
                    ack_ok &= (self.up_vals[rows, w:] == 0).all(axis=1)
            self.down_vals[rows, :w] = vals
            self.down_vals[rows, w:] = 0
            self.down_exists[rows] = exists
        else:
            self.up_vals[rows, :w] = vals
            self.up_vals[rows, w:] = 0
            self.up_exists[rows] = exists
        keys = (rows.astype(np.int64) << 1) | (1 if side else 0)
        slots = self._staged_slot[keys].astype(np.int64)
        fresh = slots < 0
        n_new = int(fresh.sum())
        if n_new:
            self._ensure_staged_capacity(self._staged_n + n_new)
            new_slots = np.arange(self._staged_n, self._staged_n + n_new)
            slots[fresh] = new_slots
            self._staged_slot[keys[fresh]] = new_slots
            self._staged_keys[new_slots] = keys[fresh]
            self._staged_rows[slots] = rows
            self._staged_n += n_new
        self._staged_vals[slots, :w] = vals
        self._staged_vals[slots, w:] = 0
        self._staged_flags[slots] = (exists.astype(np.uint32)
                                     | (2 if side else 0) | 4)
        self._staged_ack[slots] = ack_ok if ack_ok is not None else False

    @property
    def dirty(self) -> bool:
        return (bool(self._staged_n) or bool(self._staged_masks)
                or self._stale or self._pl_staged)

    # -------------------------------------------------------------- tick

    def _device_state(self) -> ReconcileState:
        # placement lanes: real when a placement owner registered (the
        # splitter's roots), minimal placeholders otherwise — either way
        # the program IS the flagship step, lanes and all (placement
        # rows are row-sharded too — pad to the row factor)
        f = self._row_factor
        if self.R:
            replicas, avail = self.pl_replicas, self.pl_avail
            r, p = self.R, self.P
        else:
            r = ((8 + f - 1) // f) * f
            p = 8
            replicas = np.zeros(r, np.int32)
            avail = np.zeros((r, p), bool)
        l, c = 1, 8
        state = ReconcileState(
            up_vals=self.up_vals, up_exists=self.up_exists,
            down_vals=self.down_vals, down_exists=self.down_exists,
            status_mask=self.status_mask,
            replicas=replicas,
            avail=avail,
            current=np.zeros((r, p), np.int32),
            pair_hashes=np.zeros((self.B, l), np.uint32),
            sel_hashes=np.zeros(c, np.uint32),
        )
        if self.mesh is not None:
            from ..parallel.mesh import shard_state

            return shard_state(state, self.mesh)
        return jax.tree.map(jax.device_put, state)

    def submit(self) -> tuple[jax.Array, tuple[int, int]] | None:
        """Upload staged events, run one fused step, return the wire array
        (with copy_to_host_async issued) plus the (patch_capacity, P)
        needed to unpack it. None if nothing to do."""
        if not self.dirty:
            return None
        t0 = time.perf_counter()
        s = self.S
        was_stale = self._stale
        if self._stale:
            self._state = self._device_state()
            self._stale = False
            self._clear_staged()
            self._pl_staged = False
            self.stats["full_uploads"] += 1
            # a full upload re-submits every owned row — they are all
            # suspects if this step fails (quarantine bisection input)
            self._last_rows = sorted(self.row_owner)
            # full upload replaces the mirrors wholesale; still run the
            # step so decisions for the new state come back
            buf_slot, packed, acks = self._wire_bufs.acquire(
                MIN_EVENTS, s + 2, self.ack_capacity)
        else:
            if self._pl_staged:
                # placement inputs changed (roots staged/retired): swap
                # ONLY the small replicas/avail leaves — never the [B,S]
                # mirrors (shapes are stable here; growth marks stale)
                self._pl_staged = False
                reps, avail = self.pl_replicas.copy(), self.pl_avail.copy()
                if self.mesh is not None:
                    from ..parallel.mesh import state_shardings

                    sh = state_shardings(self.mesh)
                    reps = jax.device_put(reps, sh["placement_rows"])
                    avail = jax.device_put(avail, sh["placement"])
                else:
                    reps = jax.device_put(reps)
                    avail = jax.device_put(avail)
                self._state = self._state._replace(replicas=reps, avail=avail)
            # the staged buffers already hold the packed-wire layout
            # (vals / row / flags, the unpack_deltas format) — one padded
            # block copy and a reset of the slot map finish the pack.
            # Ack-eligible slots ship on the 4-byte acks lane instead;
            # mask stamps for newly-allocated rows append as MASK_STAMP
            # entries (vals columns = the bool mask row).
            n = self._staged_n
            ack_sel = self._staged_ack[:n]
            na = int(ack_sel.sum())
            nf = n - na
            nm = len(self._staged_masks)
            d = pad_pow2(nf + nm, floor=MIN_EVENTS)
            # always ship the acks array, even all-padding: an acks=None
            # fast path would be a SECOND jit trace variant, and the
            # first ack-bearing tick would then compile it mid-serving —
            # a seconds-long loop stall (measured) vs the ~nothing an
            # all-dropped scatter pass costs per tick
            while self.ack_capacity < na:
                self.ack_capacity *= 2
            buf_slot, packed, acks = self._wire_bufs.acquire(
                d, s + 2, self.ack_capacity)
            if na:
                self.stats["acked"] += na
                full_sel = ~ack_sel
                packed[:nf, :s] = self._staged_vals[:n][full_sel]
                packed[:nf, s] = self._staged_rows[:n][full_sel]
                packed[:nf, s + 1] = self._staged_flags[:n][full_sel]
                acks[:na] = self._staged_rows[:n][ack_sel]
            else:
                packed[:n, :s] = self._staged_vals[:n]
                packed[:n, s] = self._staged_rows[:n]
                packed[:n, s + 1] = self._staged_flags[:n]
            if nm:
                mrows = np.fromiter(self._staged_masks, np.uint32, nm)
                masks = np.stack(list(self._staged_masks.values()))
                packed[nf:nf + nm, : masks.shape[1]] = masks.astype(np.uint32)
                packed[nf:nf + nm, s] = mrows
                packed[nf:nf + nm, s + 1] = 4 | MASK_STAMP_BIT
            rows_touched = set(self._staged_rows[:n].tolist())
            rows_touched.update(self._staged_masks)
            self._last_rows = sorted(rows_touched)
            self._clear_staged()
        t1 = time.perf_counter()
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            packed = jax.device_put(packed, repl)
            acks = jax.device_put(acks, repl)
        else:
            packed = jax.device_put(packed)
            acks = jax.device_put(acks)
        # the staging buffers may be re-acquired only after these device
        # arrays materialize (async dispatch: device_put can still be
        # reading the host memory after it returns)
        self._wire_bufs.commit(buf_slot, packed, acks)
        t2 = time.perf_counter()
        _phase("put", t2 - t1)
        k = min(self.patch_capacity, self.B)
        # KCP_FAULTS `device.step` injection point (raise@tick / error /
        # poison_row): fires HERE, where a real XLA dispatch failure
        # would surface — the quarantine machinery recovers either way
        faults.maybe_fail("device.step", rows=self._last_rows)
        self._state, wire = self._step(
            self._state, packed, acks, patch_capacity=k,
            use_pallas=self.use_pallas, mesh=self.mesh,
        )
        self._step_failures = 0
        wire.copy_to_host_async()
        t3 = time.perf_counter()
        # a stale tick's t1-t0 is the whole-mirror device upload, not the
        # steady-state pack — keep the histograms separable
        _phase("full_upload" if was_stale else "pack", t1 - t0)
        _phase("step_dispatch", t3 - t2)
        self.stats["ticks"] += 1
        return wire, (k, int(self._state.avail.shape[1]))

    # ------------------------------------------------------- quarantine

    def probe_rows(self, rows: Sequence[int]) -> bool:
        """Run one trial step over a synthetic wire carrying only
        ``rows`` (both sides, from the host mirrors), discarding the
        result. True iff the step completed — the bisection's oracle.

        The probe jit does NOT donate: the resident state must survive
        an arbitrary number of probes. Probe wire shapes are pow2-padded,
        so a bisection compiles at most a handful of variants (this is
        the rare failure path; docs/operations.md covers the cost)."""
        if self.B == 0:
            return True
        rows = [int(r) for r in rows]
        try:
            faults.maybe_fail("device.step", rows=rows)
            if self._probe_step is None:
                self._probe_step = jax.jit(
                    reconcile_step_packed,
                    static_argnames=("patch_capacity", "use_pallas", "mesh"))
            if self._state is None:
                self._state = self._device_state()
                self._stale = False
            s = self.S
            d = pad_pow2(max(2 * len(rows), 1), floor=MIN_EVENTS)
            packed = np.zeros((d, s + 2), np.uint32)
            for i, row in enumerate(rows):
                packed[2 * i, :s] = self.up_vals[row]
                packed[2 * i, s] = row
                packed[2 * i, s + 1] = (1 if self.up_exists[row] else 0) | 4
                packed[2 * i + 1, :s] = self.down_vals[row]
                packed[2 * i + 1, s] = row
                packed[2 * i + 1, s + 1] = (
                    (1 if self.down_exists[row] else 0) | 2 | 4)
            acks = np.full(self.ack_capacity, -1, np.int32)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                repl = NamedSharding(self.mesh, PartitionSpec())
                packed = jax.device_put(packed, repl)
                acks = jax.device_put(acks, repl)
            _state, wire = self._probe_step(
                self._state, packed, acks,
                patch_capacity=min(self.patch_capacity, self.B),
                use_pallas=self.use_pallas, mesh=self.mesh)
            np.asarray(wire)  # force execution; async backends defer errors
            return True
        except Exception:  # noqa: BLE001 — any failure means "poisoned"
            return False

    def bisect_poison(self, suspects: Sequence[int],
                      max_probes: int = BISECT_MAX_PROBES) -> list[int] | None:
        """Isolate the rows whose presence makes the step fail, by
        group-testing probe steps (~k*log2(n) probes for k poisons).

        Returns None when even an EMPTY probe fails — the failure is
        row-independent and quarantine cannot help. If the probe budget
        runs out, the unresolved remainder is quarantined wholesale
        (innocents may be swept up; degraded beats dead, and their
        requeue brings them back)."""
        if not self.probe_rows([]):
            return None
        return _group_test_poison(
            self.probe_rows, [[int(r) for r in suspects]], max_probes)

    def note_step_failure(self) -> None:
        self.stats["step_failures"] += 1
        self._step_failures += 1

    def quarantine_row(self, row: int) -> tuple[object | None, Section | None]:
        """Evict one poisoned row: zero its host mirrors (the pending
        full re-upload then excludes it from the resident state), free
        the row, and return (key, section) so the core can requeue the
        key to its owner with bounded backoff. One bad object must never
        stall its bucket's co-tenants."""
        sec = self.row_owner.get(row)
        key = sec.row_keys.get(row) if sec is not None else None
        self.up_vals[row] = 0
        self.down_vals[row] = 0
        self.up_exists[row] = False
        self.down_exists[row] = False
        self.status_mask[row] = False
        if sec is not None:
            if key is not None:
                sec.rows.pop(key, None)
            sec.row_keys.pop(row, None)
            self.row_owner.pop(row, None)
            self._free.append(row)
        self.stats["quarantined"] += 1
        REGISTRY.counter(
            "quarantined_rows",
            "rows evicted from fused buckets by poison-row quarantine").inc()
        self.mark_stale()
        return key, sec

    # ----------------------------------------------------------- routing

    def dispatch(self, wire: np.ndarray, meta: tuple[int, int]) -> bool:
        """Route a collected wire's patches (and dirty placement rows) to
        their owners.

        Returns True if the patch set overflowed (caller re-ticks after
        doubling capacity)."""
        idx, code, upsync, overflow, _stats = unpack_patches(wire)
        self.route_patches(idx, code, upsync)
        if self.placement_owner is not None:
            k, p = meta
            rows, counts = unpack_placement(wire, k, p)
            self.route_placement(rows, counts)
        if overflow:
            self.note_overflow()
        return bool(overflow)

    def route_patches(self, idx: np.ndarray, code: np.ndarray,
                      upsync: np.ndarray) -> None:
        """Route patch rows (bucket-local indices) to their owning
        sections — shared by the per-bucket dispatch and the fleet batch
        (which splits a fleet wire's patches by row range first)."""
        per_section: dict[Section, list[tuple[object, int, bool]]] = {}
        dropped = 0
        for r, c, u in zip(idx.tolist(), code.tolist(), upsync.tolist()):
            s = self.row_owner.get(r)
            key = s.row_keys.get(r) if s is not None else None
            if key is None:
                # an unowned/unkeyed patch row (released section, freed or
                # quarantined row, in-flight wire racing a retirement):
                # benign by design, but it must be COUNTED, not silent
                dropped += 1
                if r not in self._dropped_logged:
                    self._dropped_logged.add(r)
                    log.warning(
                        "fused-core: dropping patch for row %d (%s); "
                        "counted in fused_dropped_patch_rows", r,
                        "no owning section" if s is None else "no key mapping")
                continue
            per_section.setdefault(s, []).append((key, c, u))
        if dropped:
            REGISTRY.counter(
                "fused_dropped_patch_rows",
                "patch rows dropped at dispatch because their row had no "
                "owner/key (released, freed, or quarantined)").inc(dropped)
        for s, patches in per_section.items():
            s.owner.fused_apply(patches)

    def route_placement(self, rows: np.ndarray, counts: np.ndarray) -> None:
        """Route dirty placement rows (bucket-local) to the placement
        owner."""
        if self.placement_owner is None:
            return
        applies = []
        for i, row in enumerate(rows.tolist()):
            key = self.pl_row_keys.get(row)
            if key is not None:
                # copy: a view would pin the whole wire buffer in the
                # applier queue / retry cache
                applies.append((key, counts[i].copy()))
        if applies:
            self.placement_owner.placement_apply(applies)

    def note_overflow(self) -> None:
        self.stats["overflows"] += 1
        self.patch_capacity = min(self.patch_capacity * 2, max(self.B, MIN_ROWS))


class FleetMeta(NamedTuple):
    """Per-submit layout snapshot riding with an in-flight fleet wire.

    The fleet layout can change while a wire is still in flight (bucket
    growth, new buckets, placement widening all mark the fleet stale for
    the NEXT tick) — collection must unpack against the layout the wire
    was built under, never the current one."""

    k: int                      # patch capacity submitted
    p: int                      # placement width in the wire
    r_total: int                # placement rows in the wire
    members: tuple              # member buckets, layout order
    bases: tuple[int, ...]      # fleet row base per member
    ends: tuple[int, ...]       # fleet row end (base + B) per member
    pl_members: tuple           # members contributing placement rows
    pl_bases: tuple[int, ...]
    pl_ends: tuple[int, ...]
    seg_capacity: int


class FleetBatch:
    """One ragged device batch for the whole bucket fleet.

    Per-bucket dispatch pays full dispatch/pipeline latency per schema
    bucket — small and ragged buckets leave the chip idle between kicks.
    The fleet batch packs EVERY bucket's rows into one unified
    ReconcileState (rows range-partitioned by bucket, slot columns
    zero-padded to the widest member, per-row status masks — the [B, S]
    form the kernels already take) so a reconcile tick is ONE pipelined
    ``reconcile_step_fleet`` no matter how many buckets exist, and the
    mesh shardings in parallel/mesh.py spread that single batch over all
    devices. Results scatter back to per-bucket patch streams on collect
    (row ranges -> bucket.route_patches), so engines observe byte-
    identical patch streams vs per-bucket dispatch — the differential-
    fuzz contract.

    Per-row *segment ids* (the owning section) ride the batch as a
    resident int32 lane; the step returns per-segment live-row counts on
    the wire tail, which the core forwards to the admission quota ledger
    (admission accounting rides the same batch, no host-side pass).

    Degraded mode preserves the PR 2 semantics: a failed step retries
    once wholesale, then bisects *by segment* — the group test is seeded
    with one group per member bucket, so poison isolates within its own
    segment and only the poison rows are quarantined (via the owning
    bucket, which requeues the keys with bounded backoff).
    """

    def __init__(self, core: "FusedCore"):
        self.core = core
        self.mesh = core.mesh
        self.use_pallas = core.use_pallas
        self._members: list[FusedBucket] = []
        self._bases: list[int] = []
        self._ends: list[int] = []
        self._pl_members: list[FusedBucket] = []
        self._pl_bases: list[int] = []
        self._pl_ends: list[int] = []
        self._layout_key: tuple | None = None
        self.B = 0
        self.S = 0
        self.R = 0
        self.P = 8
        self._state: ReconcileState | None = None
        self._seg_ids = None  # device int32 [B]: row -> section segment
        self._seg_capacity = 8
        self._stale = True
        self.ack_capacity = 1024
        self._wire_bufs = WireBuffers(PIPELINE_DEPTH)
        self.donate = _resolve_donate()
        self._step = jax.jit(
            reconcile_step_fleet,
            donate_argnums=(0, 1) if self.donate else (),
            static_argnames=("patch_capacity", "seg_capacity",
                             "use_pallas", "mesh"),
        )
        self._probe_step = None
        self._last_rows: list[int] = []
        self._step_failures = 0
        self.stats = {"ticks": 0, "full_uploads": 0, "overflows": 0,
                      "acked": 0, "step_failures": 0, "quarantined": 0}

    # ----------------------------------------------------------- layout

    def _refresh_layout(self) -> None:
        members = list(self.core.buckets.values())
        key = tuple((id(b), b.B, b.S, b.R, b.P) for b in members)
        if key == self._layout_key:
            return
        self._layout_key = key
        self._members = members
        self._bases, self._ends = [], []
        base, s = 0, 0
        for b in members:
            self._bases.append(base)
            base += b.B
            self._ends.append(base)
            s = max(s, b.S)
        self.B = base
        self.S = s
        self._pl_members, self._pl_bases, self._pl_ends = [], [], []
        r, p = 0, 8
        for b in members:
            if b.R:
                self._pl_members.append(b)
                self._pl_bases.append(r)
                r += b.R
                self._pl_ends.append(r)
                p = max(p, b.P)
        self.R = r
        self.P = p
        # any layout change invalidates the resident fleet state: row
        # bases moved, so a full re-upload rebuilds it (bucket growth is
        # pow2 + rare, same cost class as a bucket's own growth)
        self._stale = True

    @property
    def dirty(self) -> bool:
        return self._stale or any(b.dirty
                                  for b in self.core.buckets.values())

    def mark_stale(self) -> None:
        self._stale = True

    def _locate(self, fleet_row: int) -> tuple[FusedBucket, int]:
        """(owning bucket, bucket-local row) for a fleet row index."""
        for b, base, end in zip(self._members, self._bases, self._ends):
            if base <= fleet_row < end:
                return b, fleet_row - base
        raise KeyError(f"fleet row {fleet_row} outside layout (B={self.B})")

    # ------------------------------------------------------------ state

    def _placement_leaves(self) -> tuple[np.ndarray, np.ndarray, int, int]:
        f = self._members[0]._row_factor if self._members else 1
        if self.R:
            r, p = self.R, self.P
            replicas = np.zeros(r, np.int32)
            avail = np.zeros((r, p), bool)
            for b, pb in zip(self._pl_members, self._pl_bases):
                replicas[pb:pb + b.R] = b.pl_replicas
                avail[pb:pb + b.R, :b.P] = b.pl_avail
        else:
            r = ((8 + f - 1) // f) * f
            p = 8
            replicas = np.zeros(r, np.int32)
            avail = np.zeros((r, p), bool)
        return replicas, avail, r, p

    def _device_state(self) -> tuple[ReconcileState, jax.Array]:
        """The concatenated fleet state + the row->segment lane, sharded
        like any bucket state (rows over tenants/hosts, slots over the
        slots axis; the seg lane shards like the exists flags)."""
        s = self.S
        up_vals = np.zeros((self.B, s), np.uint32)
        down_vals = np.zeros((self.B, s), np.uint32)
        up_exists = np.zeros(self.B, bool)
        down_exists = np.zeros(self.B, bool)
        status_mask = np.zeros((self.B, s), bool)
        seg = np.full(self.B, SEG_NONE, np.int32)
        for b, base in zip(self._members, self._bases):
            end = base + b.B
            up_vals[base:end, :b.S] = b.up_vals
            down_vals[base:end, :b.S] = b.down_vals
            up_exists[base:end] = b.up_exists
            down_exists[base:end] = b.down_exists
            status_mask[base:end, :b.S] = b.status_mask
            for row, sec in b.row_owner.items():
                if sec.seg is not None:
                    seg[base + row] = sec.seg
        replicas, avail, r, p = self._placement_leaves()
        state = ReconcileState(
            up_vals=up_vals, up_exists=up_exists,
            down_vals=down_vals, down_exists=down_exists,
            status_mask=status_mask,
            replicas=replicas, avail=avail,
            current=np.zeros((r, p), np.int32),
            pair_hashes=np.zeros((self.B, 1), np.uint32),
            sel_hashes=np.zeros(8, np.uint32),
        )
        if self.mesh is not None:
            from ..parallel.mesh import shard_state, state_shardings

            return (shard_state(state, self.mesh),
                    jax.device_put(seg, state_shardings(self.mesh)["flags"]))
        return jax.tree.map(jax.device_put, state), jax.device_put(seg)

    # ------------------------------------------------------------- tick

    def _patch_capacity(self) -> int:
        # member patch capacities pool into the fleet wire, so one
        # bucket's overflow-doubled budget benefits the whole batch
        return min(sum(b.patch_capacity for b in self._members), self.B)

    def submit(self) -> tuple[jax.Array, FleetMeta] | None:
        """Pack every dirty bucket's staged rows into one ragged batch,
        run ONE fused step, return the wire (copy_to_host_async issued)
        plus the layout snapshot needed to unpack it at collect time."""
        if not self.dirty:
            return None
        self._refresh_layout()
        if not self._members:
            return None
        t0 = time.perf_counter()
        s = self.S
        self._seg_capacity = pad_pow2(max(self.core._next_seg, 1), floor=8)
        was_stale = self._stale or any(b._stale for b in self._members)
        local_rows: list[int] = []  # bucket-local ids for KCP_FAULTS
        if was_stale:
            self._state, self._seg_ids = self._device_state()
            self._stale = False
            self._last_rows = []
            for b, base in zip(self._members, self._bases):
                b._stale = False
                b._clear_staged()
                b._pl_staged = False
                b.stats["full_uploads"] += 1
                owned = sorted(b.row_owner)
                local_rows.extend(owned)
                self._last_rows.extend(base + r for r in owned)
            self.stats["full_uploads"] += 1
            buf_slot, packed, acks = self._wire_bufs.acquire(
                MIN_EVENTS, s + 2, self.ack_capacity)
        else:
            if any(b._pl_staged for b in self._members):
                for b in self._members:
                    b._pl_staged = False
                replicas, avail, _r, _p = self._placement_leaves()
                if self.mesh is not None:
                    from ..parallel.mesh import state_shardings

                    sh = state_shardings(self.mesh)
                    reps = jax.device_put(replicas, sh["placement_rows"])
                    av = jax.device_put(avail, sh["placement"])
                else:
                    reps = jax.device_put(replicas)
                    av = jax.device_put(avail)
                self._state = self._state._replace(replicas=reps, avail=av)
            # gather the members' staged arrays (already the packed-wire
            # layout) into one fleet wire: row indices shift by the
            # member's base, ack-eligible slots pool on one acks lane,
            # mask stamps gain the owning section's segment id
            per: list[tuple] = []
            nf_total = na_total = nm_total = 0
            for b, base in zip(self._members, self._bases):
                n = b._staged_n
                ack_sel = b._staged_ack[:n]
                na = int(ack_sel.sum())
                nm = len(b._staged_masks)
                per.append((b, base, n, ack_sel, na, nm))
                nf_total += n - na
                na_total += na
                nm_total += nm
            d = pad_pow2(nf_total + nm_total, floor=MIN_EVENTS)
            # fleet acks capacity honors each member's sticky high-water
            # (bench pre-warms bucket.ack_capacity to dodge mid-serving
            # recompiles — the fleet must not undo that)
            cap = max(self.ack_capacity,
                      max((b.ack_capacity for b in self._members),
                          default=1024))
            while cap < na_total:
                cap *= 2
            self.ack_capacity = cap
            buf_slot, packed, acks = self._wire_bufs.acquire(d, s + 2, cap)
            pos = apos = 0
            self._last_rows = []
            for b, base, n, ack_sel, na, nm in per:
                w = b.S
                if n:
                    if na:
                        full_sel = ~ack_sel
                        nf = n - na
                        packed[pos:pos + nf, :w] = b._staged_vals[:n][full_sel]
                        packed[pos:pos + nf, s] = (
                            b._staged_rows[:n][full_sel] + np.uint32(base))
                        packed[pos:pos + nf, s + 1] = (
                            b._staged_flags[:n][full_sel])
                        acks[apos:apos + na] = (
                            b._staged_rows[:n][ack_sel].astype(np.int32)
                            + base)
                        apos += na
                        b.stats["acked"] += na
                        self.stats["acked"] += na
                        pos += nf
                    else:
                        packed[pos:pos + n, :w] = b._staged_vals[:n]
                        packed[pos:pos + n, s] = (
                            b._staged_rows[:n] + np.uint32(base))
                        packed[pos:pos + n, s + 1] = b._staged_flags[:n]
                        pos += n
                if nm:
                    mrows = np.fromiter(b._staged_masks, np.uint32, nm)
                    masks = np.stack(list(b._staged_masks.values()))
                    packed[pos:pos + nm, :masks.shape[1]] = (
                        masks.astype(np.uint32))
                    packed[pos:pos + nm, s] = mrows + np.uint32(base)
                    segs = np.fromiter(
                        (sec.seg if (sec := b.row_owner.get(r)) is not None
                         and sec.seg is not None else SEG_NONE
                         for r in b._staged_masks), np.uint32, nm)
                    packed[pos:pos + nm, s + 1] = (
                        4 | MASK_STAMP_BIT | (segs << SEG_SHIFT))
                    pos += nm
                touched = set(b._staged_rows[:n].tolist())
                touched.update(b._staged_masks)
                local_rows.extend(touched)
                self._last_rows.extend(base + r for r in sorted(touched))
                b._clear_staged()
        t1 = time.perf_counter()
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            packed_d = jax.device_put(packed, repl)
            acks_d = jax.device_put(acks, repl)
        else:
            packed_d = jax.device_put(packed)
            acks_d = jax.device_put(acks)
        self._wire_bufs.commit(buf_slot, packed_d, acks_d)
        t2 = time.perf_counter()
        _phase("put", t2 - t1)
        k = self._patch_capacity()
        # KCP_FAULTS `device.step`: rows are BUCKET-LOCAL ids (the union
        # across members), so a poison_row spec targets the same logical
        # rows whether dispatch is per-bucket or fleet-wide — the
        # differential fuzz relies on it
        faults.maybe_fail("device.step", rows=local_rows)
        self._state, self._seg_ids, wire = self._step(
            self._state, self._seg_ids, packed_d, acks_d,
            patch_capacity=k, seg_capacity=self._seg_capacity,
            use_pallas=self.use_pallas, mesh=self.mesh,
        )
        self._step_failures = 0
        wire.copy_to_host_async()
        t3 = time.perf_counter()
        _phase("full_upload" if was_stale else "pack", t1 - t0)
        _phase("step_dispatch", t3 - t2)
        self.stats["ticks"] += 1
        # member tick counters advance too: the fleet step covers every
        # bucket's rows, and engines/benches read their bucket's counter
        for b in self._members:
            b.stats["ticks"] += 1
        REGISTRY.counter(
            "fused_fleet_ticks_total",
            "fleet-wide ragged batch steps dispatched").inc()
        REGISTRY.gauge(
            "fused_fleet_rows", "rows in the fleet batch").set(self.B)
        REGISTRY.gauge(
            "fused_fleet_buckets",
            "schema buckets packed into the fleet batch").set(
            len(self._members))
        REGISTRY.gauge(
            "fused_fleet_segments",
            "registered sections (fleet segments)").set(
            len(self.core._segments))
        meta = FleetMeta(
            k=k, p=int(self._state.avail.shape[1]),
            r_total=int(self._state.replicas.shape[0]),
            members=tuple(self._members), bases=tuple(self._bases),
            ends=tuple(self._ends), pl_members=tuple(self._pl_members),
            pl_bases=tuple(self._pl_bases), pl_ends=tuple(self._pl_ends),
            seg_capacity=self._seg_capacity,
        )
        return wire, meta

    # ---------------------------------------------------------- routing

    def dispatch(self, wire: np.ndarray, meta: FleetMeta) -> bool:
        """Scatter a collected fleet wire back to per-bucket patch
        streams: split patches and placement rows by the row ranges of
        the submitting layout, then route through each member's own
        section/placement routing. Returns True on patch overflow."""
        idx, code, upsync, overflow, _stats = unpack_patches(wire)
        if idx.size:
            ends = np.asarray(meta.ends, np.int64)
            mi = np.searchsorted(ends, idx, side="right")
            for j, b in enumerate(meta.members):
                sel = mi == j
                if sel.any():
                    b.route_patches(idx[sel] - meta.bases[j],
                                    code[sel], upsync[sel])
        if meta.pl_members:
            rows, counts = unpack_placement(wire, meta.k, meta.p,
                                            r=meta.r_total)
            if rows.size:
                pl_ends = np.asarray(meta.pl_ends, np.int64)
                pmi = np.searchsorted(pl_ends, rows, side="right")
                for j, b in enumerate(meta.pl_members):
                    sel = pmi == j
                    if sel.any():
                        pw = min(b.P, meta.p)
                        b.route_placement(rows[sel] - meta.pl_bases[j],
                                          counts[sel][:, :pw])
        # per-segment live-row counts -> the admission quota ledger
        self.core._publish_fleet_counts(
            unpack_seg_counts(wire, meta.k, meta.r_total, meta.p,
                              meta.seg_capacity))
        if overflow:
            self.stats["overflows"] += 1
            for b in meta.members:
                b.note_overflow()
        return bool(overflow)

    # ------------------------------------------------------- quarantine

    def note_step_failure(self) -> None:
        self.stats["step_failures"] += 1
        self._step_failures += 1
        for b in self._members:
            b.stats["step_failures"] += 1

    def probe_rows(self, rows: Sequence[int]) -> bool:
        """The fleet bisection oracle: one non-donating trial step over a
        synthetic wire carrying only ``rows`` (fleet ids), rebuilt from
        the owning buckets' host mirrors. True iff the step completed."""
        if self.B == 0:
            return True
        rows = [int(r) for r in rows]
        locs = [self._locate(r) for r in rows]
        try:
            faults.maybe_fail("device.step", rows=[lr for _b, lr in locs])
            if self._probe_step is None:
                self._probe_step = jax.jit(
                    reconcile_step_fleet,
                    static_argnames=("patch_capacity", "seg_capacity",
                                     "use_pallas", "mesh"))
            if self._state is None:
                self._state, self._seg_ids = self._device_state()
                self._stale = False
            s = self.S
            d = pad_pow2(max(2 * len(rows), 1), floor=MIN_EVENTS)
            packed = np.zeros((d, s + 2), np.uint32)
            for i, ((b, lr), fr) in enumerate(zip(locs, rows)):
                packed[2 * i, :b.S] = b.up_vals[lr]
                packed[2 * i, s] = fr
                packed[2 * i, s + 1] = (1 if b.up_exists[lr] else 0) | 4
                packed[2 * i + 1, :b.S] = b.down_vals[lr]
                packed[2 * i + 1, s] = fr
                packed[2 * i + 1, s + 1] = (
                    (1 if b.down_exists[lr] else 0) | 2 | 4)
            acks = np.full(self.ack_capacity, -1, np.int32)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                repl = NamedSharding(self.mesh, PartitionSpec())
                packed = jax.device_put(packed, repl)
                acks = jax.device_put(acks, repl)
            _state, _seg, wire = self._probe_step(
                self._state, self._seg_ids, packed, acks,
                patch_capacity=self._patch_capacity(),
                seg_capacity=self._seg_capacity,
                use_pallas=self.use_pallas, mesh=self.mesh)
            np.asarray(wire)  # force execution; async backends defer errors
            return True
        except Exception:  # noqa: BLE001 — any failure means "poisoned"
            return False

    def bisect_poison(self, suspects: Sequence[int],
                      max_probes: int = BISECT_MAX_PROBES) -> list[int] | None:
        """Segment-scoped bisection over the ragged batch: the group test
        is seeded with one suspect group per member bucket, so a clean
        segment clears in one probe and poison isolates within its own
        segment. None when even the empty probe fails (systemic)."""
        if not self.probe_rows([]):
            return None
        groups: dict[int, list[int]] = {}
        for r in suspects:
            b, _lr = self._locate(int(r))
            groups.setdefault(id(b), []).append(int(r))
        return _group_test_poison(self.probe_rows, list(groups.values()),
                                  max_probes)

    def quarantine_row(self, row: int) -> tuple[object | None, Section | None]:
        """Evict one poisoned fleet row via its owning bucket (which
        zeroes the mirrors, frees the row, marks itself stale — forcing
        the fleet re-upload — and hands back the key for requeue)."""
        b, lr = self._locate(int(row))
        self.stats["quarantined"] += 1
        return b.quarantine_row(lr)


class FusedCore:
    """The per-loop serving core: one tick loop over all fused buckets."""

    _instances: dict[int, "FusedCore"] = {}
    # process-default admission quota ledger (set_process_ledger): the
    # sink for the fleet batch's device-side per-segment counters
    _process_ledger = None

    def __init__(self, mesh=None, batch_window: float = 0.002,
                 use_pallas: bool | None = None,
                 pipeline: str | None = None,
                 fleet: bool | None = None):
        self.mesh = mesh
        if use_pallas is None:
            use_pallas = os.environ.get("KCP_PALLAS", "") == "1"
        self.use_pallas = use_pallas
        # fleet-wide ragged batching (default on): every tick packs all
        # dirty buckets into ONE pipelined device program. KCP_FLEET_BATCH=0
        # is the fallback knob — per-bucket dispatch, the A/B reference
        # for bench.py --fleet and the ragged differential fuzz
        if fleet is None:
            fleet = os.environ.get("KCP_FLEET_BATCH", "1").lower() not in (
                "0", "false", "off")
        self.fleet_mode = fleet
        self._fleet = FleetBatch(self) if fleet else None
        self._segments: dict[int, Section] = {}  # seg id -> section
        self._next_seg = 0
        self.ledger = FusedCore._process_ledger
        # tick pipelining mode: "double" (default) keeps up to
        # PIPELINE_DEPTH steps in flight per bucket — pack N+1 and apply
        # N-1 while the device runs N; "serial" collects every wire in
        # the tick that submitted it (the A/B reference for bench.py
        # --pipeline and the equivalence fuzz)
        if pipeline is None:
            pipeline = os.environ.get("KCP_PIPELINE", "") or "double"
        if pipeline not in PIPELINE_MODES:
            raise ValueError(f"pipeline must be one of {PIPELINE_MODES}, "
                             f"got {pipeline!r}")
        self.pipeline = pipeline
        self.fetch_depth = PIPELINE_DEPTH if pipeline == "double" else 0
        REGISTRY.gauge(
            "fused_pipeline_window",
            "configured in-flight tick window (0 = serial mode)",
        ).set(self.fetch_depth)
        self.buckets: dict[int, FusedBucket] = {}
        self.controller = BatchController(
            "fused-core", self._process_batch, batch_window=batch_window,
            overlap_drain=(pipeline == "double"),
        )
        self._inflight: list[
            tuple[FusedBucket, jax.Array, tuple[int, int]]
        ] = []
        self._flush_task: asyncio.Task | None = None
        self._eager_collect: bool | None = None  # resolved on first flush
        # quarantined keys awaiting their bounded-backoff requeue
        self._quarantine_retries: dict[tuple[int, object], int] = {}
        self._refs = 0
        self._started = False
        self._stopping = False
        self._stop_done: asyncio.Event | None = None
        self._loop = None

    # ---------------------------------------------------------- lifecycle

    @classmethod
    def for_current_loop(cls, mesh=None,
                         pipeline: str | None = None) -> "FusedCore":
        """The process-wide core for the running asyncio loop (tests run
        many loops sequentially; each gets a fresh core).

        ``mesh=None`` falls back to the process serving mesh
        (parallel.mesh.set_serving_mesh — the server's Config.mesh /
        --mesh flag), so a configured process serves sharded without
        every engine re-plumbing the mesh. ``pipeline=None`` falls back
        to ``KCP_PIPELINE`` (default "double")."""
        if mesh is None:
            from ..parallel.mesh import get_serving_mesh

            mesh = get_serving_mesh()
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        core = cls._instances.get(id(loop))
        # the identity check guards against id() reuse after a dead loop
        # is garbage-collected: a stale core's tick task died with its loop
        if core is None or core._closed() or core._loop is not loop:
            core = cls(mesh=mesh, pipeline=pipeline)
            core._loop = loop
            cls._instances[id(loop)] = core
        else:
            if mesh is not None and core.mesh != mesh:
                log.warning("FusedCore for this loop already exists with a "
                            "different mesh; keeping the existing core's mesh")
            if pipeline is not None and core.pipeline != pipeline:
                log.warning("FusedCore for this loop already exists with "
                            "pipeline=%s; keeping it", core.pipeline)
        return core

    @classmethod
    def set_process_ledger(cls, ledger) -> None:
        """Install the admission quota ledger the fleet batch's device-
        side per-segment counters feed (server.py wires this when the
        admission chain has a quota ledger). Applies to live cores too."""
        cls._process_ledger = ledger
        for core in cls._instances.values():
            core.ledger = ledger

    def _publish_fleet_counts(self, seg_counts: np.ndarray) -> None:
        """Forward a collected fleet wire's per-segment live-row counts
        to the quota ledger, keyed by each owning section's
        ``fused_ledger_key()`` (sections without one don't account)."""
        ledger = self.ledger
        if ledger is None:
            return
        counts: dict[tuple, int] = {}
        released = []
        for seg, section in self._segments.items():
            if section.released:
                released.append(seg)
                continue
            if seg >= seg_counts.shape[0]:
                continue
            keyfn = getattr(section.owner, "fused_ledger_key", None)
            key = keyfn() if keyfn is not None else None
            if key is None:
                continue
            counts[key] = counts.get(key, 0) + int(seg_counts[seg])
        for seg in released:
            del self._segments[seg]
        if counts:
            ledger.ingest_device_counts(counts)
            REGISTRY.counter(
                "fused_fleet_ledger_updates_total",
                "device-side per-segment count batches forwarded to the "
                "quota ledger").inc()

    def _closed(self) -> bool:
        return self._started and self._refs == 0

    async def start(self) -> None:
        self._refs += 1
        if not self._started:
            self._started = True
            await self.controller.start()

    async def stop(self) -> None:
        if self._refs > 0:
            self._refs -= 1
        if self._refs > 0 or not self._started:
            return
        if self._stopping:
            # double-stop (or stop concurrent with an in-flight stop):
            # an idempotent no-op — wait for the first stop's drain so
            # every caller returns to a fully-drained core
            if self._stop_done is not None:
                await self._stop_done.wait()
            return
        self._stopping = True
        self._stop_done = asyncio.Event()
        try:
            # controller first: its shutdown drain runs the FINAL ticks,
            # and those submits append in-flight wires — draining
            # _inflight before the tick loop exits would strand (and
            # silently drop) the last window's patches (proven by the
            # pipeline shutdown/drain test)
            await self.controller.stop()
            if self._flush_task is not None:
                self._flush_task.cancel()
                self._flush_task = None
            await self._drain_inflight()
            # drop the registry entry so closed cores (and their device-
            # resident bucket state) do not accumulate across loops
            for k, v in list(FusedCore._instances.items()):
                if v is self:
                    del FusedCore._instances[k]
        finally:
            self._stop_done.set()

    # ------------------------------------------------------------ plumbing

    def bucket(self, slots: int) -> FusedBucket:
        b = self.buckets.get(slots)
        if b is None:
            b = FusedBucket(slots, mesh=self.mesh, use_pallas=self.use_pallas,
                            always_stamp=self.fleet_mode)
            self.buckets[slots] = b
        return b

    def register(self, owner: SectionOwner, slots: int) -> Section:
        section = self.bucket(slots).section(owner)
        # fleet segment id: stable for the section's lifetime; retired
        # ids are not reused (the capacity is pow2-padded and tiny)
        section.seg = self._next_seg
        self._segments[self._next_seg] = section
        self._next_seg += 1
        return section

    def register_placement(self, owner, p: int = 8,
                           slots: int = 64) -> FusedBucket:
        """Attach a placement owner (the deployment splitter) to the
        default bucket — its roots then ride the SAME fused step that
        serves the sync sections."""
        b = self.bucket(slots)
        b.register_placement(owner, p)
        return b

    def kick(self, bucket: FusedBucket) -> None:
        """Request a tick for a bucket dirtied outside the section path
        (placement staging)."""
        self.controller.queue.add(("__kick__", False, id(bucket), None))

    def enqueue(self, section: Section, side: bool, key) -> None:
        self.controller.enqueue((id(section.owner), side, key, section))

    def enqueue_many(self, section: Section, side: bool, keys) -> None:
        """Batch enqueue a churn/feedback key set (one queue crossing)."""
        oid = id(section.owner)
        self.controller.enqueue_many(
            [(oid, side, key, section) for key in keys])

    # ---------------------------------------------------------------- tick

    async def _process_batch(self, items: Sequence) -> list:
        # 1. encode touched keys (engines re-read their informer caches);
        #    section=None items are retick markers — their bucket is
        #    already marked stale and will re-run on this tick. Items
        #    whose section was released (engine stop or vocabulary
        #    migration) are stale: touching them would resurrect rows in
        #    the old bucket — drop them, the replacement section was
        #    re-enqueued with the same keys.
        t0 = time.perf_counter()
        # wall-clock tick anchor for convergence attribution: the engine
        # stamps which dispatch carried a traced row by pairing this with
        # its fused_apply callback time (kcp_tpu/obs — phase "tick")
        self.last_tick_start = time.time()
        # per key, remember WHICH side(s) this batch's events touched —
        # an informer event changes exactly one mirror side (the
        # reference's two controllers each watch one apiserver,
        # pkg/syncer/specsyncer.go:43-55 / statussyncer.go:29-39), so an
        # existing row ships only that side's wire entry; mask bit 1 = up,
        # bit 2 = down
        touched: dict[Section, dict] = {}
        for _oid, side, key, section in items:
            if section is not None and not section.released:
                km = touched.setdefault(section, {})
                km[key] = km.get(key, 0) | (2 if side else 1)
        for section, keymasks in touched.items():
            self._encode_section(section, keymasks)
        if touched:
            _phase("encode", time.perf_counter() - t0)

        # 2. one fused step per dirty bucket; collection is pipelined.
        #    Occupancy telemetry per submit: how deep the in-flight window
        #    already was (depth histogram) and whether this dispatch
        #    overlapped an executing step (the pipeline's whole point)
        inflight_by_bucket: dict[int, int] = {}
        for b, _w, _m in self._inflight:
            inflight_by_bucket[id(b)] = inflight_by_bucket.get(id(b), 0) + 1
        depth_h = REGISTRY.histogram(
            "fused_pipeline_depth",
            "in-flight steps per bucket at submit time",
            buckets=DEPTH_BUCKETS)
        # fleet mode: ONE ragged batch covers every dirty bucket — the
        # same pipelined window applies, with the fleet as the unit
        submitters = ((self._fleet,) if self._fleet is not None
                      else tuple(self.buckets.values()))
        for bucket in submitters:
            try:
                submitted = bucket.submit()
            except Exception as err:  # noqa: BLE001 — degraded-mode gate
                if self._recover_step_failure(bucket, err):
                    continue
                # surface loudly: a row-independent submit failure (bad
                # sharding, systemic device error) otherwise dies as 5
                # silent INFO-level retries
                log.exception("fused-core: %s submit failed "
                              "(B=%d S=%d mesh=%s)",
                              type(bucket).__name__, bucket.B, bucket.S,
                              bucket.mesh is not None)
                raise
            if submitted is not None:
                wire, meta = submitted
                depth = inflight_by_bucket.get(id(bucket), 0)
                depth_h.observe(depth)
                if depth:
                    REGISTRY.counter(
                        "fused_pipeline_overlap_ticks_total",
                        "submits issued while a previous step was still "
                        "in flight (overlapped ticks)").inc()
                self._inflight.append((bucket, wire, meta))

        # 3. collect: per BUCKET, oldest in-flight wires beyond the
        #    pipeline window (blocking is fine by then — their data has
        #    had fetch_depth full ticks to land; serial mode, depth 0,
        #    collects everything including this tick's own wire). Depth
        #    is per bucket so one bucket's fresh wire never forces a
        #    zero-depth blocking collect of another's.
        #    (Measured and rejected: collecting already-ready wires
        #    opportunistically — on a synchronous backend every wire is
        #    instantly "ready", which serializes dispatch into the tick
        #    and cost ~15% throughput at bench scale.)
        counts: dict[int, int] = {}
        for b, _w, _m in self._inflight:
            counts[id(b)] = counts.get(id(b), 0) + 1
        i = 0
        while i < len(self._inflight):
            b, w, m = self._inflight[i]
            if counts[id(b)] > self.fetch_depth:
                self._inflight.pop(i)
                counts[id(b)] -= 1
                self._collect(b, w, m)
            else:
                i += 1
        if self._inflight:
            self._schedule_flush()
        return []

    # ------------------------------------------------ degraded-mode path

    def _recover_step_failure(self, bucket, err: Exception) -> bool:
        """Survive a failed device step without stalling the bucket's
        co-tenants: retry once wholesale (full re-upload rebuilds the
        resident state from the host mirrors — the source of truth), and
        on a second consecutive failure bisect the submitted rows to
        quarantine the poison. ``bucket`` is a FusedBucket or the
        FleetBatch (whose bisection is segment-scoped and whose
        quarantine routes through the owning member bucket). Returns
        False when the failure is row-independent (the caller then
        propagates it)."""
        bucket.note_step_failure()
        REGISTRY.counter(
            "fused_step_failures_total",
            "fused device-step submissions that raised").inc()
        if bucket._step_failures == 1:
            log.warning("fused-core: device step failed (%s: %s); retrying "
                        "once with a full re-upload", type(err).__name__, err)
            bucket.mark_stale()
            self.controller.queue.add(("__retick__", False, id(bucket), None))
            return True
        suspects = list(bucket._last_rows)
        bad = bucket.bisect_poison(suspects)
        if bad is None:
            # even the empty probe fails: systemic. Propagate — but keep
            # the bucket dirty: the failed submit already consumed the
            # staged events and cleared _stale, so without this the
            # controller's retried items would find nothing to submit
            # and the bucket would wedge converged-looking forever
            bucket.mark_stale()
            return False
        for row in bad:
            key, section = bucket.quarantine_row(row)
            log.warning("fused-core: quarantined row %d (key=%r) after "
                        "repeated device-step failures", row, key)
            if key is not None and section is not None:
                self._requeue_quarantined(section, key)
        bucket._step_failures = 0
        bucket.mark_stale()
        self.controller.queue.add(("__retick__", False, id(bucket), None))
        return True

    def _requeue_quarantined(self, section: Section, key) -> None:
        """Hand a quarantined key back to its owner after a bounded
        exponential backoff — level-triggered recovery: if the poison was
        transient the re-staged row converges; if not, the next failing
        tick re-quarantines it at a longer (capped) delay."""
        qk = (id(section), key)
        n = self._quarantine_retries.get(qk, 0)
        self._quarantine_retries[qk] = n + 1
        delay = min(QUARANTINE_BASE_BACKOFF * (2 ** n), QUARANTINE_MAX_BACKOFF)
        REGISTRY.counter(
            "fused_quarantine_requeues_total",
            "quarantined keys scheduled for an owner requeue").inc()
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (sync drivers): the next owner event recovers

        def _requeue() -> None:
            if not section.released:
                self.enqueue(section, False, key)

        loop.call_later(delay, _requeue)

    def _encode_section(self, section: Section, keymasks: dict) -> None:
        from ..ops.encode import BucketOverflow

        bucket = section.bucket
        keys = list(keymasks)
        # a key new to the bucket must initialize BOTH device mirror
        # sides; an existing row ships only the side(s) its events touched
        masks = np.fromiter(
            (keymasks[k] | (0 if k in section.rows else 3) for k in keys),
            np.uint8, len(keys))
        many = getattr(section.owner, "fused_encode_many", None)
        try:
            if many is not None:
                up_v, up_e, down_v, down_e = many(keys)
            else:
                ups, upes, downs, downes = [], [], [], []
                for key in keys:
                    u, ue, dv, de = section.owner.fused_encode(key)
                    ups.append(u)
                    upes.append(ue)
                    downs.append(dv)
                    downes.append(de)
                try:
                    up_v, down_v = np.stack(ups), np.stack(downs)
                except ValueError:
                    # ragged widths (an engine mid-vocabulary-migration):
                    # per-key slow path, both sides as before
                    for key, u, ue, dv, de in zip(keys, ups, upes, downs,
                                                  downes):
                        row = section.row_for(key)
                        bucket.stage(row, False, u, ue)
                        bucket.stage(row, True, dv, de)
                    section.refresh_mask()
                    return
                up_e = np.asarray(upes, bool)
                down_e = np.asarray(downes, bool)
        except BucketOverflow:
            # engine's vocabulary outgrew this bucket: the engine
            # re-registers in a larger bucket and replays its rows
            section.owner.fused_overflow()
            return
        rows = np.fromiter((section.row_for(k) for k in keys),
                           np.int64, len(keys))
        up_v, up_e = np.asarray(up_v), np.asarray(up_e)
        down_v, down_e = np.asarray(down_v), np.asarray(down_e)
        up_sel = (masks & 1) != 0
        if up_sel.all():
            bucket.stage_many(rows, False, up_v, up_e)
        elif up_sel.any():
            bucket.stage_many(rows[up_sel], False, up_v[up_sel], up_e[up_sel])
        down_sel = (masks & 2) != 0
        if down_sel.all():
            bucket.stage_many(rows, True, down_v, down_e)
        elif down_sel.any():
            bucket.stage_many(rows[down_sel], True, down_v[down_sel],
                              down_e[down_sel])
        section.refresh_mask()

    def _collect(self, bucket: FusedBucket, wire: jax.Array,
                 meta: tuple[int, int]) -> None:
        t0 = time.perf_counter()
        # fetch blocks ONLY on the compact wire (copy_to_host_async was
        # issued at dispatch) — never on the donated resident state. The
        # ready split is the pipeline-occupancy answer: a blocked fetch
        # means the host outran the device by the full window.
        try:
            ready = bool(wire.is_ready())
        except AttributeError:  # plain ndarray in tests
            ready = True
        REGISTRY.counter(
            "fused_collect_ready_total" if ready
            else "fused_collect_blocked_total",
            "fetches that found the wire already on host (ready) vs had "
            "to wait for the device (blocked)").inc()
        host_wire = np.asarray(wire)
        t1 = time.perf_counter()
        overflow = bucket.dispatch(host_wire, meta)
        _phase("collect_wait", t1 - t0)
        _phase("dispatch", time.perf_counter() - t1)
        if overflow:
            # level-triggered: re-run the bucket with doubled capacity
            bucket.mark_stale()
            self.controller.queue.add(("__retick__", False, id(bucket), None))

    def _schedule_flush(self) -> None:
        if self._flush_task is not None:
            self._flush_task.cancel()
        self._flush_task = asyncio.create_task(self._idle_flush())

    async def _idle_flush(self) -> None:
        """Collect in-flight wires off the tick path.

        On an asynchronous backend (TPU), this polls ``wire.is_ready()``
        between ticks and collects the moment the device finishes —
        patches apply ~one device round trip after dispatch instead of
        waiting for the NEXT tick's depth-based collect (about a full
        tick of convergence latency under continuous load), and it never
        blocks a submit because only ready wires are popped. On the
        synchronous CPU backend every wire is instantly "ready", so eager
        collection would serialize dispatch into the loop (measured ~15%
        of serving throughput) — there, keep the original behavior: only
        collect once the loop has been quiet for IDLE_FLUSH_S (without
        which the last tick's patches would wait for the next informer
        event)."""
        if self._eager_collect is None:
            try:
                self._eager_collect = jax.default_backend() != "cpu"
            except Exception:  # noqa: BLE001 — backend init failure
                self._eager_collect = False
        try:
            if not self._eager_collect:
                await asyncio.sleep(IDLE_FLUSH_S)
            while self._inflight:
                bucket, wire, meta = self._inflight[0]
                # exponential poll backoff: a tunnel-attached device has
                # ~tens-of-ms round trips, so a flat 1 ms poll would wake
                # the loop ~100x per wire for no data; cap at 8 ms so a
                # ready wire is still collected promptly
                poll = 0.001
                while not wire.is_ready():
                    await asyncio.sleep(poll)
                    poll = min(poll * 2, 0.008)
                # the head can change across the awaits (a tick's depth-
                # based collect pops it, and a collect failure means
                # _schedule_flush never cancelled this task) — pop only
                # the wire this iteration actually inspected
                if not self._inflight or self._inflight[0][1] is not wire:
                    continue
                self._inflight.pop(0)
                self._collect(bucket, wire, meta)
        except asyncio.CancelledError:
            pass

    async def _drain_inflight(self) -> None:
        while self._inflight:
            self._collect(*self._inflight.pop(0))
