"""FusedCore — the served control plane runs the flagship device program.

The reference runs one goroutine pair per (cluster, GVR)
(pkg/syncer/syncer.go:46-64 StartSyncer); round 1 of this build ran one
small device program per (cluster, GVR). This module closes the gap
between the benched program and the served one: every sync engine in the
process registers a row *section* inside a shared schema bucket, and each
reconcile tick runs ONE fused ``reconcile_step_packed`` per bucket —
resident donated state, packed one-array-each-way wire format, pipelined
collection — exactly the artifact ``bench.py`` measures.

Topology:

  FusedCore ── one per asyncio loop (the process's serving loop)
    ├── BatchController      one tick loop draining all engines' events
    └── FusedBucket(S)       one per slot capacity (the schema bucket)
          ├── ReconcileState device-resident [B, S] mirrors + per-row
          │                  status masks (engines have different slot
          │                  vocabularies, so masks are [B, S])
          └── Section        one per engine: a set of rows + callbacks

Tick pipeline (the UPLOAD_LEAD/FETCH_DEPTH structure proven in bench.py):

  drain events -> engines encode touched keys -> bucket stages rows
    -> pack ONE uint32 delta array, device_put, step (donated), wire out
    -> wire.copy_to_host_async(); collection happens a tick later (or via
       the idle flusher) without blocking the loop
    -> unpack patches, route rows to owning sections, engines' appliers
       take it from there (also without blocking the tick)

Patch overflow: the wire carries at most ``patch_capacity`` actionable
rows. Because the loop is level-triggered (every tick re-decides every
row), overflow loses nothing — the core doubles capacity (one recompile)
and re-ticks.

Mesh serving: pass ``mesh=`` to shard every bucket's state over a
(tenants, slots) device mesh — same layout as ``parallel/mesh.py`` and
``dryrun_multichip``. Stats reductions lower to cross-device collectives;
the packed wire batch is replicated (it is O(events), not O(fleet)).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Protocol, Sequence

import jax
import numpy as np

from ..models.reconcile_model import (
    PACK_HDR,
    ReconcileState,
    reconcile_step_packed,
    unpack_patches,
    unpack_placement,
)
from ..ops.encode import pad_pow2
from ..reconciler.controller import BatchController

log = logging.getLogger(__name__)

MIN_ROWS = 64
MIN_EVENTS = 64
MIN_PATCH_CAPACITY = 256
FETCH_DEPTH = 1  # in-flight ticks before a blocking collect
IDLE_FLUSH_S = 0.003  # collect leftovers when no new tick arrives


class SectionOwner(Protocol):
    """What an engine provides to its section (see BatchSyncEngine)."""

    def fused_encode(self, key) -> tuple[np.ndarray, bool, np.ndarray, bool]:
        """(up_vals[S], up_exists, down_vals[S], down_exists) for a key,
        re-read from the informer caches. May raise BucketOverflow."""
        ...

    def fused_status_mask(self) -> np.ndarray:
        """bool[S] — the engine's current status-slot mask."""
        ...

    def fused_apply(self, patches: list[tuple[object, int, bool]]) -> None:
        """Receive (key, decision_code, upsync) patches for this engine's
        rows. Must not block the loop (hand off to an applier pool)."""
        ...

    def fused_overflow(self) -> None:
        """The engine's slot vocabulary outgrew its bucket: grow the
        encoder, re-register in a larger bucket, replay all rows."""
        ...


class Section:
    """One engine's row allocation inside a bucket."""

    def __init__(self, bucket: "FusedBucket", owner: SectionOwner):
        self.bucket = bucket
        self.owner = owner
        self.rows: dict[object, int] = {}  # key -> global row
        self.row_keys: dict[int, object] = {}  # global row -> key
        # seed the mask cache now: row_for stamps every new row with the
        # current mask, so refresh_mask must only fire on real changes
        self._mask: np.ndarray = owner.fused_status_mask().copy()
        self.released = False

    def row_for(self, key) -> int:
        row = self.rows.get(key)
        if row is None:
            row = self.bucket.alloc_row(self)
            self.rows[key] = row
            self.row_keys[row] = key
            # stamp with the cached mask; refresh_mask restamps everything
            # if the owner's vocabulary has drifted since
            self.bucket.status_mask[row, : self._mask.shape[0]] = self._mask
        return row

    def refresh_mask(self) -> None:
        """Restamp this section's rows after the owner's vocabulary grew
        new status slots (rare; triggers a full re-upload)."""
        mask = self.owner.fused_status_mask()
        if np.array_equal(self._mask, mask):
            return
        self._mask = mask.copy()
        for row in self.rows.values():
            self.bucket.status_mask[row] = False
            self.bucket.status_mask[row, : mask.shape[0]] = mask
        self.bucket.mark_stale()

    def release(self) -> None:
        self.released = True
        for row in self.rows.values():
            self.bucket.free_row(row)
        self.rows.clear()
        self.row_keys.clear()


class FusedBucket:
    """One schema bucket: host staging + device-resident fused state."""

    def __init__(self, slots: int, mesh=None, use_pallas: bool = False):
        self.S = slots
        self.B = 0
        self.mesh = mesh
        # the fused Pallas decision+fanout pass (ops/pallas_kernels.py);
        # on a mesh it runs per device via shard_map (reconcile_model
        # gates on local-row divisibility and falls back to XLA lanes)
        self.use_pallas = use_pallas
        # sharded state must device_put cleanly: row counts are padded to
        # a multiple of the row-axis product (see _grow), and the slots
        # axis must divide the (power-of-two) slot capacity up front
        self._row_factor = 1
        if mesh is not None:
            from ..parallel.mesh import row_factor, slot_factor

            self._row_factor = row_factor(mesh)
            slot_dim = slot_factor(mesh)
            if slots % slot_dim:
                raise ValueError(
                    f"bucket slot capacity {slots} is not divisible by the "
                    f"mesh slots axis ({slot_dim}); use a power-of-two "
                    f"slots axis"
                )
        self.up_vals = np.zeros((0, slots), np.uint32)
        self.down_vals = np.zeros((0, slots), np.uint32)
        self.up_exists = np.zeros(0, bool)
        self.down_exists = np.zeros(0, bool)
        self.status_mask = np.zeros((0, slots), bool)
        self.sections: list[Section] = []
        self.row_owner: dict[int, Section] = {}
        self._free: list[int] = []
        self._next = 0
        # placement lanes (the deployment splitter's serving section):
        # root rows with replicas + per-cluster availability, returned as
        # compacted dirty rows in the wire's placement segment
        self.placement_owner = None
        self.P = 8
        self.R = 0
        self.pl_replicas = np.zeros(0, np.int32)
        self.pl_avail = np.zeros((0, 8), bool)
        self.pl_rows: dict[object, int] = {}
        self.pl_row_keys: dict[int, object] = {}
        self._pl_free: list[int] = []
        self._pl_next = 0
        self._pl_staged = False
        self._state: ReconcileState | None = None
        self._stale = True
        self.patch_capacity = MIN_PATCH_CAPACITY
        # staged events for the next tick: (row, side) -> (vals, exists)
        self._staged: dict[tuple[int, bool], tuple[np.ndarray, bool]] = {}
        self._step = jax.jit(
            reconcile_step_packed, donate_argnums=(0,),
            static_argnames=("patch_capacity", "use_pallas", "mesh"),
        )
        self.stats = {"ticks": 0, "full_uploads": 0, "overflows": 0}

    # ------------------------------------------------------------- rows

    def section(self, owner: SectionOwner) -> Section:
        s = Section(self, owner)
        self.sections.append(s)
        return s

    def alloc_row(self, section: Section) -> int:
        if self._free:
            row = self._free.pop()
        else:
            if self._next >= self.B:
                self._grow(self._next + 1)
            row = self._next
            self._next += 1
        self.row_owner[row] = section
        return row

    def free_row(self, row: int) -> None:
        self.up_exists[row] = self.down_exists[row] = False
        self.up_vals[row] = self.down_vals[row] = 0
        self.row_owner.pop(row, None)
        self._free.append(row)
        self.mark_stale()

    def _grow(self, needed: int) -> None:
        new_b = pad_pow2(max(needed, MIN_ROWS))
        if new_b % self._row_factor:
            # non-power-of-two row sharding (e.g. a 5-device tenants
            # axis): round up so every row dimension device_puts cleanly
            new_b += self._row_factor - new_b % self._row_factor

        def grow(a, shape, dtype):
            out = np.zeros(shape, dtype)
            out[: a.shape[0], ...] = a
            return out

        self.up_vals = grow(self.up_vals, (new_b, self.S), np.uint32)
        self.down_vals = grow(self.down_vals, (new_b, self.S), np.uint32)
        self.up_exists = grow(self.up_exists, (new_b,), bool)
        self.down_exists = grow(self.down_exists, (new_b,), bool)
        self.status_mask = grow(self.status_mask, (new_b, self.S), bool)
        self.B = new_b
        self.mark_stale()

    def mark_stale(self) -> None:
        self._stale = True

    # -------------------------------------------------------- placement

    def register_placement(self, owner, p: int = 8) -> None:
        """Attach the deployment splitter as this bucket's placement
        owner: its roots ride the replicas/avail lanes of the SAME fused
        step that serves the sync sections (VERDICT r3 item 5 — the
        serving tick computes real placement, not zeros)."""
        if self.placement_owner is not None and self.placement_owner is not owner:
            raise RuntimeError("bucket already has a placement owner")
        self.placement_owner = owner
        self.P = pad_pow2(max(p, 1), floor=8)
        if self.pl_avail.shape[1] != self.P:
            old = self.pl_avail
            self.pl_avail = np.zeros((old.shape[0], self.P), bool)
            self.pl_avail[:, : old.shape[1]] = old[:, : self.P]
            self.mark_stale()

    def pl_row_for(self, key) -> int:
        row = self.pl_rows.get(key)
        if row is None:
            if self._pl_free:
                row = self._pl_free.pop()
            else:
                if self._pl_next >= self.R:
                    self._pl_grow(self._pl_next + 1)
                row = self._pl_next
                self._pl_next += 1
            self.pl_rows[key] = row
            self.pl_row_keys[row] = key
        return row

    def free_pl_row(self, key) -> None:
        row = self.pl_rows.pop(key, None)
        if row is None:
            return
        self.pl_row_keys.pop(row, None)
        self.pl_replicas[row] = 0
        self.pl_avail[row] = False
        self._pl_free.append(row)
        # the device-resident `current` still holds this row's last split;
        # a future occupant staging inputs whose split EQUALS it would
        # never re-dirty — rebuild the resident state (root retirement is
        # rare relative to ticks, so the full upload is acceptable)
        self.mark_stale()

    def invalidate_placement(self) -> None:
        """Force every placement row to re-emit on the next tick (rebuilds
        the resident state, zeroing `current`). Used when a host-side
        apply rejected device counts — identical re-staged inputs would
        otherwise never re-dirty."""
        self.mark_stale()

    def _pl_grow(self, needed: int) -> None:
        new_r = pad_pow2(max(needed, 8))
        if new_r % self._row_factor:
            new_r += self._row_factor - new_r % self._row_factor
        reps = np.zeros(new_r, np.int32)
        reps[: self.R] = self.pl_replicas
        avail = np.zeros((new_r, self.P), bool)
        avail[: self.R] = self.pl_avail
        self.pl_replicas, self.pl_avail = reps, avail
        self.R = new_r
        # shape change: the resident current[R,P] must be rebuilt too
        self.mark_stale()

    def stage_placement(self, key, replicas: int, n_clusters: int) -> None:
        """Stage one root's desired placement inputs (replicas + how many
        of the P cluster slots are available). The width grows on demand
        — P is a padding floor, never a silent cap (matching the host
        splitter's 'width follows the widest row' contract)."""
        row = self.pl_row_for(key)
        if n_clusters > self.P:
            self._pl_widen(pad_pow2(n_clusters, floor=8))
        self.pl_replicas[row] = replicas
        self.pl_avail[row] = False
        self.pl_avail[row, :n_clusters] = True
        self._pl_staged = True

    def _pl_widen(self, new_p: int) -> None:
        avail = np.zeros((self.R, new_p), bool)
        avail[:, : self.P] = self.pl_avail
        self.pl_avail = avail
        self.P = new_p
        # shape change: resident avail/current must be rebuilt
        self.mark_stale()

    # ------------------------------------------------------------ events

    def stage(self, row: int, side: bool, vals: np.ndarray, exists: bool) -> None:
        """Stage one delta event (last-wins per (row, side)) and mirror it
        into host staging (the rebuild source of truth)."""
        self._staged[(row, side)] = (vals, exists)
        if side:
            self.down_vals[row, : vals.shape[0]] = vals
            self.down_vals[row, vals.shape[0]:] = 0
            self.down_exists[row] = exists
        else:
            self.up_vals[row, : vals.shape[0]] = vals
            self.up_vals[row, vals.shape[0]:] = 0
            self.up_exists[row] = exists

    @property
    def dirty(self) -> bool:
        return bool(self._staged) or self._stale or self._pl_staged

    # -------------------------------------------------------------- tick

    def _device_state(self) -> ReconcileState:
        # placement lanes: real when a placement owner registered (the
        # splitter's roots), minimal placeholders otherwise — either way
        # the program IS the flagship step, lanes and all (placement
        # rows are row-sharded too — pad to the row factor)
        f = self._row_factor
        if self.R:
            replicas, avail = self.pl_replicas, self.pl_avail
            r, p = self.R, self.P
        else:
            r = ((8 + f - 1) // f) * f
            p = 8
            replicas = np.zeros(r, np.int32)
            avail = np.zeros((r, p), bool)
        l, c = 1, 8
        state = ReconcileState(
            up_vals=self.up_vals, up_exists=self.up_exists,
            down_vals=self.down_vals, down_exists=self.down_exists,
            status_mask=self.status_mask,
            replicas=replicas,
            avail=avail,
            current=np.zeros((r, p), np.int32),
            pair_hashes=np.zeros((self.B, l), np.uint32),
            sel_hashes=np.zeros(c, np.uint32),
        )
        if self.mesh is not None:
            from ..parallel.mesh import shard_state

            return shard_state(state, self.mesh)
        return jax.tree.map(jax.device_put, state)

    def submit(self) -> tuple[jax.Array, tuple[int, int]] | None:
        """Upload staged events, run one fused step, return the wire array
        (with copy_to_host_async issued) plus the (patch_capacity, P)
        needed to unpack it. None if nothing to do."""
        if not self.dirty:
            return None
        s = self.S
        if self._stale:
            self._state = self._device_state()
            self._stale = False
            self._staged.clear()
            self._pl_staged = False
            self.stats["full_uploads"] += 1
            # full upload replaces the mirrors wholesale; still run the
            # step so decisions for the new state come back
            packed = np.zeros((MIN_EVENTS, s + 2), np.uint32)
        else:
            if self._pl_staged:
                # placement inputs changed (roots staged/retired): swap
                # ONLY the small replicas/avail leaves — never the [B,S]
                # mirrors (shapes are stable here; growth marks stale)
                self._pl_staged = False
                reps, avail = self.pl_replicas.copy(), self.pl_avail.copy()
                if self.mesh is not None:
                    from ..parallel.mesh import state_shardings

                    sh = state_shardings(self.mesh)
                    reps = jax.device_put(reps, sh["placement_rows"])
                    avail = jax.device_put(avail, sh["placement"])
                else:
                    reps = jax.device_put(reps)
                    avail = jax.device_put(avail)
                self._state = self._state._replace(replicas=reps, avail=avail)
            # build the packed wire array directly — vectorized: one
            # np.stack instead of a per-event python copy loop (the loop
            # was ~30% of serving wall time at bench scale; flags are
            # exists | side<<1 | valid<<2, the unpack_deltas layout)
            staged = self._staged
            self._staged = {}
            n = len(staged)
            d = pad_pow2(n, floor=MIN_EVENTS)
            packed = np.zeros((d, s + 2), np.uint32)
            vals = [ve[0] for ve in staged.values()]
            try:
                stacked = np.stack(vals)
            except ValueError:
                # ragged widths (an engine mid-migration): slow path
                for i, v in enumerate(vals):
                    packed[i, : v.shape[0]] = v
            else:
                packed[:n, : stacked.shape[1]] = stacked
            packed[:n, s] = np.fromiter(
                (row for row, _sd in staged), np.uint32, n)
            packed[:n, s + 1] = np.fromiter(
                ((1 if ex else 0) | (2 if sd else 0) | 4
                 for (_row, sd), (_v, ex) in staged.items()),
                np.uint32, n)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            packed = jax.device_put(packed, NamedSharding(self.mesh, PartitionSpec()))
        else:
            packed = jax.device_put(packed)
        k = min(self.patch_capacity, self.B)
        self._state, wire = self._step(
            self._state, packed, patch_capacity=k,
            use_pallas=self.use_pallas, mesh=self.mesh,
        )
        wire.copy_to_host_async()
        self.stats["ticks"] += 1
        return wire, (k, int(self._state.avail.shape[1]))

    def dispatch(self, wire: np.ndarray, meta: tuple[int, int]) -> bool:
        """Route a collected wire's patches (and dirty placement rows) to
        their owners.

        Returns True if the patch set overflowed (caller re-ticks after
        doubling capacity)."""
        idx, code, upsync, overflow, _stats = unpack_patches(wire)
        per_section: dict[Section, list[tuple[object, int, bool]]] = {}
        for r, c, u in zip(idx.tolist(), code.tolist(), upsync.tolist()):
            s = self.row_owner.get(r)
            if s is None:
                continue
            key = s.row_keys.get(r)
            if key is not None:
                per_section.setdefault(s, []).append((key, c, u))
        for s, patches in per_section.items():
            s.owner.fused_apply(patches)
        if self.placement_owner is not None:
            k, p = meta
            rows, counts = unpack_placement(wire, k, p)
            applies = []
            for i, row in enumerate(rows.tolist()):
                key = self.pl_row_keys.get(row)
                if key is not None:
                    # copy: a view would pin the whole wire buffer in the
                    # applier queue / retry cache
                    applies.append((key, counts[i].copy()))
            if applies:
                self.placement_owner.placement_apply(applies)
        if overflow:
            self.stats["overflows"] += 1
            self.patch_capacity = min(self.patch_capacity * 2, max(self.B, MIN_ROWS))
        return bool(overflow)


class FusedCore:
    """The per-loop serving core: one tick loop over all fused buckets."""

    _instances: dict[int, "FusedCore"] = {}

    def __init__(self, mesh=None, batch_window: float = 0.002,
                 use_pallas: bool | None = None):
        self.mesh = mesh
        if use_pallas is None:
            import os

            use_pallas = os.environ.get("KCP_PALLAS", "") == "1"
        self.use_pallas = use_pallas
        self.buckets: dict[int, FusedBucket] = {}
        self.controller = BatchController(
            "fused-core", self._process_batch, batch_window=batch_window
        )
        self._inflight: list[tuple[FusedBucket, jax.Array]] = []
        self._flush_task: asyncio.Task | None = None
        self._refs = 0
        self._started = False
        self._loop = None

    # ---------------------------------------------------------- lifecycle

    @classmethod
    def for_current_loop(cls, mesh=None) -> "FusedCore":
        """The process-wide core for the running asyncio loop (tests run
        many loops sequentially; each gets a fresh core).

        ``mesh=None`` falls back to the process serving mesh
        (parallel.mesh.set_serving_mesh — the server's Config.mesh /
        --mesh flag), so a configured process serves sharded without
        every engine re-plumbing the mesh."""
        if mesh is None:
            from ..parallel.mesh import get_serving_mesh

            mesh = get_serving_mesh()
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        core = cls._instances.get(id(loop))
        # the identity check guards against id() reuse after a dead loop
        # is garbage-collected: a stale core's tick task died with its loop
        if core is None or core._closed() or core._loop is not loop:
            core = cls(mesh=mesh)
            core._loop = loop
            cls._instances[id(loop)] = core
        elif mesh is not None and core.mesh != mesh:
            log.warning("FusedCore for this loop already exists with a "
                        "different mesh; keeping the existing core's mesh")
        return core

    def _closed(self) -> bool:
        return self._started and self._refs == 0

    async def start(self) -> None:
        self._refs += 1
        if not self._started:
            self._started = True
            await self.controller.start()

    async def stop(self) -> None:
        self._refs -= 1
        if self._refs > 0:
            return
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        await self._drain_inflight()
        await self.controller.stop()
        # drop the registry entry so closed cores (and their device-
        # resident bucket state) do not accumulate across loops
        for k, v in list(FusedCore._instances.items()):
            if v is self:
                del FusedCore._instances[k]

    # ------------------------------------------------------------ plumbing

    def bucket(self, slots: int) -> FusedBucket:
        b = self.buckets.get(slots)
        if b is None:
            b = FusedBucket(slots, mesh=self.mesh, use_pallas=self.use_pallas)
            self.buckets[slots] = b
        return b

    def register(self, owner: SectionOwner, slots: int) -> Section:
        return self.bucket(slots).section(owner)

    def register_placement(self, owner, p: int = 8,
                           slots: int = 64) -> FusedBucket:
        """Attach a placement owner (the deployment splitter) to the
        default bucket — its roots then ride the SAME fused step that
        serves the sync sections."""
        b = self.bucket(slots)
        b.register_placement(owner, p)
        return b

    def kick(self, bucket: FusedBucket) -> None:
        """Request a tick for a bucket dirtied outside the section path
        (placement staging)."""
        self.controller.queue.add(("__kick__", False, id(bucket), None))

    def enqueue(self, section: Section, side: bool, key) -> None:
        self.controller.enqueue((id(section.owner), side, key, section))

    def enqueue_many(self, section: Section, side: bool, keys) -> None:
        """Batch enqueue a churn/feedback key set (one queue crossing)."""
        oid = id(section.owner)
        self.controller.enqueue_many(
            [(oid, side, key, section) for key in keys])

    # ---------------------------------------------------------------- tick

    async def _process_batch(self, items: Sequence) -> list:
        # 1. encode touched keys (engines re-read their informer caches);
        #    section=None items are retick markers — their bucket is
        #    already marked stale and will re-run on this tick. Items
        #    whose section was released (engine stop or vocabulary
        #    migration) are stale: touching them would resurrect rows in
        #    the old bucket — drop them, the replacement section was
        #    re-enqueued with the same keys.
        touched: dict[Section, set] = {}
        for _oid, _side, key, section in items:
            if section is not None and not section.released:
                touched.setdefault(section, set()).add(key)
        for section, keys in touched.items():
            self._encode_section(section, keys)

        # 2. one fused step per dirty bucket; collection is pipelined
        for bucket in self.buckets.values():
            try:
                submitted = bucket.submit()
            except Exception:
                # surface loudly: a submit failure (bad sharding, device
                # error) otherwise dies as 5 silent INFO-level retries
                log.exception("fused-core: bucket submit failed "
                              "(B=%d S=%d mesh=%s)", bucket.B, bucket.S,
                              bucket.mesh is not None)
                raise
            if submitted is not None:
                wire, meta = submitted
                self._inflight.append((bucket, wire, meta))

        # 3. collect: per BUCKET, oldest in-flight wires beyond FETCH_DEPTH
        #    (blocking is fine by then — their data has had a full tick to
        #    land). Depth is per bucket so one bucket's fresh wire never
        #    forces a zero-depth blocking collect of another's.
        #    (Measured and rejected: collecting already-ready wires
        #    opportunistically — on a synchronous backend every wire is
        #    instantly "ready", which serializes dispatch into the tick
        #    and cost ~15% throughput at bench scale.)
        counts: dict[int, int] = {}
        for b, _w, _m in self._inflight:
            counts[id(b)] = counts.get(id(b), 0) + 1
        i = 0
        while i < len(self._inflight):
            b, w, m = self._inflight[i]
            if counts[id(b)] > FETCH_DEPTH:
                self._inflight.pop(i)
                counts[id(b)] -= 1
                self._collect(b, w, m)
            else:
                i += 1
        self._schedule_flush()
        return []

    def _encode_section(self, section: Section, keys) -> None:
        from ..ops.encode import BucketOverflow

        for key in keys:
            try:
                up_v, up_e, down_v, down_e = section.owner.fused_encode(key)
            except BucketOverflow:
                # engine's vocabulary outgrew this bucket: the engine
                # re-registers in a larger bucket and replays its rows
                section.owner.fused_overflow()
                return
            row = section.row_for(key)
            section.bucket.stage(row, False, up_v, up_e)
            section.bucket.stage(row, True, down_v, down_e)
        section.refresh_mask()

    def _collect(self, bucket: FusedBucket, wire: jax.Array,
                 meta: tuple[int, int]) -> None:
        overflow = bucket.dispatch(np.asarray(wire), meta)
        if overflow:
            # level-triggered: re-run the bucket with doubled capacity
            bucket.mark_stale()
            self.controller.queue.add(("__retick__", False, id(bucket), None))

    def _schedule_flush(self) -> None:
        if self._flush_task is not None:
            self._flush_task.cancel()
        self._flush_task = asyncio.create_task(self._idle_flush())

    async def _idle_flush(self) -> None:
        """Collect remaining in-flight wires once the loop goes quiet —
        without this, the last tick's patches would wait for the next
        informer event."""
        try:
            await asyncio.sleep(IDLE_FLUSH_S)
            while self._inflight:
                bucket, wire, meta = self._inflight[0]
                while not wire.is_ready():
                    await asyncio.sleep(0.001)
                self._inflight.pop(0)
                self._collect(bucket, wire, meta)
        except asyncio.CancelledError:
            pass

    async def _drain_inflight(self) -> None:
        while self._inflight:
            self._collect(*self._inflight.pop(0))
