from .lcd import CompatError, ensure_structural_schema_compatibility

__all__ = ["ensure_structural_schema_compatibility", "CompatError"]
