"""Structural-schema compatibility + Lowest-Common-Denominator construction.

Behavioral port of the reference's negotiation math (pkg/schemacompat/
schemacompat.go:34-417) over plain JSON-schema dicts (openAPIV3Schema as
stored in CommonAPIResourceSpec). The contract:

    ensure_structural_schema_compatibility(existing, new, narrow)
        -> (lcd, errors)

checks that *existing* is a sub-schema of *new* (every document valid
under existing is valid under new, i.e. new is backward-compatible).
With ``narrow=True`` incompatibilities are resolved by narrowing: the
returned LCD accepts exactly the documents both schemas accept (where
computable), and only truly unsupported/unreconcilable constructs error.

Like the reference, unsupported JSON-Schema constructs fail closed: a
construct whose comparison is not implemented reports an incompatibility
rather than silently passing (schemacompat.go:23-26).

The engine stays host-side (irregular tree recursion); the batch-scale
path is hashing schemas to buckets on device (ops/schemahash.py) so only
distinct schemas walk this code.

One deliberate deviation: the reference's checks for ``anyOf``/``oneOf``
on strings/booleans/arrays accidentally inspect ``allOf`` (schemacompat.go
:208-209 et al.); here each construct is checked for real.
"""

from __future__ import annotations

import copy
from typing import Any

INT_OR_STRING = "x-kubernetes-int-or-string"
PRESERVE_UNKNOWN = "x-kubernetes-preserve-unknown-fields"
EMBEDDED = "x-kubernetes-embedded-resource"


class CompatError(Exception):
    """Aggregated incompatibility report."""

    def __init__(self, errors: list[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


def ensure_structural_schema_compatibility(
    existing: dict, new: dict, narrow_existing: bool = False, fld_path: str = "schema.openAPISchema"
) -> tuple[dict, list[str]]:
    """Returns (lcd, errors). ``lcd`` is meaningful when errors is empty
    (or when narrowing resolved them)."""
    lcd = copy.deepcopy(existing)
    errors: list[str] = []
    _lcd_for_structural(fld_path, existing, new, lcd, narrow_existing, errors)
    return lcd, errors


# ---------------------------------------------------------------- helpers

def _typ(s: dict | None) -> str:
    return (s or {}).get("type", "")


def _err(errors: list[str], path: str, msg: str) -> None:
    errors.append(f"{path}: {msg}")


def _check_same_type(path: str, existing: dict, new: dict, errors: list[str]) -> bool:
    if _typ(new) != _typ(existing):
        _err(errors, f"{path}.type",
             f'The type changed (was "{_typ(existing)}", now "{_typ(new)}")')
        return False
    return True


def _check_unsupported(path: str, existing: Any, new: Any, name: str, typename: str,
                       errors: list[str]) -> None:
    """Fail closed on constructs whose comparison is not implemented.

    Presence-based, not truthiness-based: ``maximum: 0`` or ``pattern: ""``
    are real constraints and must fail closed exactly like any other value
    (the reference checks nil pointers, not zero values).
    """
    if existing is not None or new is not None:
        _err(errors, path,
             f'The "{name}" JSON Schema construct is not supported by the '
             f'Schema negotiation for type "{typename}"')


def _check_numeric_validation(path: str, existing: dict, new: dict, typename: str,
                              errors: list[str]) -> None:
    for name in ("not", "allOf", "anyOf", "oneOf", "enum"):
        _check_unsupported(path, existing.get(name), new.get(name), name, typename, errors)
    if (existing.get("maximum") != new.get("maximum")
            or existing.get("minimum") != new.get("minimum")
            or bool(existing.get("exclusiveMaximum")) != bool(new.get("exclusiveMaximum"))
            or bool(existing.get("exclusiveMinimum")) != bool(new.get("exclusiveMinimum"))):
        _check_unsupported(path, existing.get("maximum"), new.get("maximum"),
                           "maximum", typename, errors)
        _check_unsupported(path, existing.get("minimum"), new.get("minimum"),
                           "minimum", typename, errors)
    if existing.get("multipleOf") != new.get("multipleOf"):
        _check_unsupported(path, existing.get("multipleOf"), new.get("multipleOf"),
                           "multipleOf", typename, errors)


# ------------------------------------------------------------ dispatcher

def _lcd_for_structural(path: str, existing: dict | None, new: dict | None, lcd: dict,
                        narrow: bool, errors: list[str]) -> None:
    if new is None:
        _err(errors, path, "new schema doesn't allow anything")
        return
    existing = existing or {}
    if bool(existing.get(PRESERVE_UNKNOWN)) != bool(new.get(PRESERVE_UNKNOWN)):
        _err(errors, f"{path}.{PRESERVE_UNKNOWN}",
             f"{PRESERVE_UNKNOWN} value changed (was {bool(existing.get(PRESERVE_UNKNOWN))}, "
             f"now {bool(new.get(PRESERVE_UNKNOWN))})")
        return

    t = _typ(existing)
    if t == "number":
        _lcd_for_number(path, existing, new, lcd, narrow, errors)
    elif t == "integer":
        _lcd_for_integer(path, existing, new, lcd, narrow, errors)
    elif t == "string":
        _lcd_for_string(path, existing, new, lcd, narrow, errors)
    elif t == "boolean":
        _lcd_for_boolean(path, existing, new, lcd, narrow, errors)
    elif t == "array":
        _lcd_for_array(path, existing, new, lcd, narrow, errors)
    elif t == "object":
        _lcd_for_object(path, existing, new, lcd, narrow, errors)
    elif t == "":
        if existing.get(INT_OR_STRING):
            _lcd_for_int_or_string(path, existing, new, lcd, narrow, errors)
        elif existing.get(PRESERVE_UNKNOWN):
            _check_same_type(path, existing, new, errors)
        elif existing.get(EMBEDDED):
            # Deliberate deviation: the reference's type dispatch
            # (schemacompat.go:144-165) has no case for a typeless
            # arbitrary node carrying only x-kubernetes-embedded-resource
            # — yet its own puller emits exactly that shape
            # (VisitArbitrary, discovery.go:325-335), so an imported
            # schema with an arbitrary subtree would fail LCD against an
            # identical copy of itself. Treat it like preserve-unknown:
            # compatible iff the new node is the same arbitrary shape.
            if bool(existing.get(EMBEDDED)) != bool(new.get(EMBEDDED)):
                _err(errors, f"{path}.{EMBEDDED}",
                     f"{EMBEDDED} value changed (was "
                     f"{bool(existing.get(EMBEDDED))}, "
                     f"now {bool(new.get(EMBEDDED))})")
            else:
                _check_same_type(path, existing, new, errors)
        else:
            _err(errors, f"{path}.type", f'Invalid type: "{t}"')
    else:
        _err(errors, f"{path}.type", f'Invalid type: "{t}"')


# ----------------------------------------------------------- per-type lcd

def _lcd_for_number(path: str, existing: dict, new: dict, lcd: dict,
                    narrow: bool, errors: list[str]) -> None:
    if _typ(new) == "integer":
        # new is a subset of existing: only acceptable when narrowing
        if not narrow:
            _check_same_type(path, existing, new, errors)
            return
        lcd["type"] = "integer"
        _check_numeric_validation(path, existing, new, "integer", errors)
        return
    if not _check_same_type(path, existing, new, errors):
        return
    _check_numeric_validation(path, existing, new, "numbers", errors)


def _lcd_for_integer(path: str, existing: dict, new: dict, lcd: dict,
                     narrow: bool, errors: list[str]) -> None:
    if _typ(new) != "number":
        # "number" widens integer: fine, LCD keeps integer
        if not _check_same_type(path, existing, new, errors):
            return
    _check_numeric_validation(path, existing, new, "integer", errors)


def _lcd_for_string_validation(path: str, existing: dict, new: dict, lcd: dict,
                               narrow: bool, errors: list[str]) -> None:
    for name in ("allOf", "anyOf", "oneOf"):
        _check_unsupported(path, existing.get(name), new.get(name), name, "string", errors)
    if (existing.get("maxLength") != new.get("maxLength")
            or existing.get("minLength") != new.get("minLength")):
        _check_unsupported(path, existing.get("maxLength"), new.get("maxLength"),
                           "maxLength", "string", errors)
        _check_unsupported(path, existing.get("minLength"), new.get("minLength"),
                           "minLength", "string", errors)
    if existing.get("pattern") != new.get("pattern"):
        _check_unsupported(path, existing.get("pattern"), new.get("pattern"),
                           "pattern", "string", errors)

    def enum_set(schema: dict) -> set[str]:
        vals = set()
        for v in schema.get("enum") or []:
            if not isinstance(v, str):
                _err(errors, f"{path}.enum",
                     "enum value should be a 'string' for Json type 'string'")
                continue
            vals.add(v)
        return vals

    existing_enum = enum_set(existing)
    new_enum = enum_set(new)
    if not new_enum.issuperset(existing_enum):
        if not narrow:
            removed = sorted(new_enum - existing_enum)
            _err(errors, f"{path}.enum",
                 f"enum value has been changed in an incompatible way ({removed})")
        inter = sorted(existing_enum & new_enum)
        if inter:
            lcd["enum"] = inter
        else:
            lcd.pop("enum", None)
    if existing.get("format") != new.get("format"):
        _err(errors, f"{path}.format", "format value has been changed in an incompatible way")


def _lcd_for_string(path: str, existing: dict, new: dict, lcd: dict,
                    narrow: bool, errors: list[str]) -> None:
    _check_same_type(path, existing, new, errors)
    _lcd_for_string_validation(path, existing, new, lcd, narrow, errors)


def _lcd_for_boolean(path: str, existing: dict, new: dict, lcd: dict,
                     narrow: bool, errors: list[str]) -> None:
    _check_same_type(path, existing, new, errors)
    for name in ("allOf", "anyOf", "oneOf", "enum"):
        _check_unsupported(path, existing.get(name), new.get(name), name, "boolean", errors)


def _lcd_for_array(path: str, existing: dict, new: dict, lcd: dict,
                   narrow: bool, errors: list[str]) -> None:
    _check_same_type(path, existing, new, errors)
    for name in ("allOf", "anyOf", "oneOf", "enum"):
        _check_unsupported(path, existing.get(name), new.get(name), name, "array", errors)
    if (existing.get("maxItems") != new.get("maxItems")
            or existing.get("minItems") != new.get("minItems")):
        _check_unsupported(path, existing.get("maxItems"), new.get("maxItems"),
                           "maxItems", "array", errors)
        _check_unsupported(path, existing.get("minItems"), new.get("minItems"),
                           "minItems", "array", errors)
    if not existing.get("uniqueItems") and new.get("uniqueItems"):
        if not narrow:
            _err(errors, f"{path}.uniqueItems",
                 "uniqueItems value has been changed in an incompatible way")
        else:
            lcd["uniqueItems"] = True
    if "items" in existing or "items" in new:
        lcd_items = lcd.setdefault("items", copy.deepcopy(existing.get("items") or {}))
        _lcd_for_structural(f"{path}.items", existing.get("items"), new.get("items"),
                            lcd_items, narrow, errors)
    if existing.get("x-kubernetes-list-type") != new.get("x-kubernetes-list-type"):
        _err(errors, f"{path}.x-kubernetes-list-type",
             "x-kubernetes-list-type value has been changed in an incompatible way")
    if set(existing.get("x-kubernetes-list-map-keys") or ()) != set(
            new.get("x-kubernetes-list-map-keys") or ()):
        _err(errors, f"{path}.x-kubernetes-list-map-keys",
             "x-kubernetes-list-map-keys value has been changed in an incompatible way")


def _lcd_for_object(path: str, existing: dict, new: dict, lcd: dict,
                    narrow: bool, errors: list[str]) -> None:
    _check_same_type(path, existing, new, errors)
    if existing.get("x-kubernetes-map-type") != new.get("x-kubernetes-map-type"):
        _err(errors, f"{path}.x-kubernetes-map-type",
             "x-kubernetes-map-type value has been changed in an incompatible way")

    # structural schemas: properties and additionalProperties are mutually
    # exclusive (schemacompat.go:323-324)
    existing_props: dict = existing.get("properties") or {}
    new_props: dict = new.get("properties") or {}
    new_ap = new.get("additionalProperties")
    existing_ap = existing.get("additionalProperties")

    if existing_props:
        if new_props:
            kept = set(existing_props)
            if not set(new_props).issuperset(kept):
                if not narrow:
                    removed = sorted(set(existing_props) - set(new_props))
                    _err(errors, f"{path}.properties",
                         f"properties have been removed in an incompatible way ({removed})")
                kept = set(existing_props) & set(new_props)
            for key in sorted(kept):
                _lcd_for_structural(f"{path}.properties[{key}]",
                                    existing_props[key], new_props[key],
                                    lcd["properties"][key], narrow, errors)
            for removed_key in set(existing_props) - kept:
                del lcd["properties"][removed_key]
        elif isinstance(new_ap, dict) and new_ap:
            for key in sorted(existing_props):
                _lcd_for_structural(f"{path}.properties[{key}]",
                                    existing_props[key], new_ap,
                                    lcd["properties"][key], narrow, errors)
        elif new_ap is True:
            pass  # new allows anything: existing stays the LCD
        else:
            _err(errors, f"{path}.properties",
                 f"properties value has been completely cleared in an incompatible way "
                 f"({sorted(existing_props)})")
    elif existing_ap is not None:
        if isinstance(existing_ap, dict) and existing_ap:
            if isinstance(new_ap, dict) and new_ap:
                _lcd_for_structural(f"{path}.additionalProperties", existing_ap, new_ap,
                                    lcd["additionalProperties"], narrow, errors)
            elif new_ap is True:
                pass  # superset: keep existing
            else:
                _err(errors, f"{path}.additionalProperties",
                     "additionalProperties value has been changed in an incompatible way")
        elif existing_ap is True:
            if new_ap is not True:
                if not narrow:
                    _err(errors, f"{path}.additionalProperties",
                         "additionalProperties value has been changed in an incompatible way")
                lcd["additionalProperties"] = copy.deepcopy(new_ap)

    for name in ("allOf", "anyOf", "oneOf", "enum"):
        _check_unsupported(path, existing.get(name), new.get(name), name, "object", errors)


def _lcd_for_int_or_string(path: str, existing: dict, new: dict, lcd: dict,
                           narrow: bool, errors: list[str]) -> None:
    _check_same_type(path, existing, new, errors)
    if not new.get(INT_OR_STRING):
        _err(errors, f"{path}.{INT_OR_STRING}",
             f"{INT_OR_STRING} value has been changed in an incompatible way")
    # int-or-string carries a fixed anyOf; compare it separately and hide it
    # from the string/integer validation passes (schemacompat.go:394-411)
    if existing.get("anyOf") != new.get("anyOf"):
        _err(errors, f"{path}.anyOf", "anyOf value has been changed in an incompatible way")
    ex = {k: v for k, v in existing.items() if k != "anyOf"}
    nw = {k: v for k, v in new.items() if k != "anyOf"}
    _lcd_for_string_validation(path, ex, nw, lcd, narrow, errors)
    _check_numeric_validation(path, ex, nw, "integer", errors)
