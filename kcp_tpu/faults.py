"""Deterministic fault injection (``KCP_FAULTS``).

The north-star loop serves 10k logical clusters out of ONE fused device
program — at that blast radius "we handle failures" is not a claim that
can rest on production incidents. This module makes every failure mode a
*replayable input*: a ``KCP_FAULTS`` spec names injection points threaded
through the store, the REST client/watch relay, the syncer apply path and
the fused device step, and a seeded per-point PRNG makes any schedule
reproducible bit-for-bit (same spec + seed + call sequence = same faults).

Spec grammar — semicolon-separated clauses, each ``point:action``::

    KCP_FAULTS="store.put:error=0.05;watch:drop@tick=200;\
device.step:raise@tick=57;syncer.apply:latency=50ms"
    KCP_FAULTS_SEED=1337

    clause  := <point> ":" <action> [ "=" <value> ] { "@" <mod> "=" <mval> }
    action  := error | raise | drop | latency | poison_row
    value   := probability (0.05) | duration (50ms, 2s) | row index
    mod     := tick | peer | heal | jitter

- ``error``      raise :class:`~kcp_tpu.utils.errors.UnavailableError`
                 (an injected 503 — exercises retry/backoff/circuit paths)
- ``raise``      raise :class:`InjectedFault` (a non-API RuntimeError —
                 exercises the crash paths, e.g. a device-step failure)
- ``drop``       ask the site to drop its stream (watch connection loss)
- ``latency``    add ``value`` seconds of delay at the site
- ``poison_row`` fire whenever the site's ``rows`` metadata contains the
                 row index in ``value`` (a persistently-poisoned wire row
                 — what the FusedCore quarantine bisection hunts)

``@tick=N`` fires exactly on the Nth invocation of the point (1-based);
without it, ``value`` is a per-invocation probability (``error``/``drop``)
or always-on (``latency``/``poison_row``; ``raise`` with no value fires
every time).

WAN-link modifiers (the ``link.*`` points)::

    link.partition:drop@peer=*>10.0.0.2:6443@heal=40
    link.delay:latency=80ms@peer=repl.feed>replica@jitter=20ms

- ``@peer=SRC>DST`` scopes the rule to one *directed* link (``SRC<>DST``
  matches both directions; either side may be ``*``). A peer-scoped rule
  only fires at link-aware sites (:func:`link_fault`), so an asymmetric
  partition — A cannot reach B while B still reaches A — is one clause.
- ``@heal=N`` heals the rule at the point's Nth invocation: it fires on
  invocations 1..N-1 and never again (the heal-at-tick lever; the
  scenario engine's phase-end injector clear is the other heal path).
- ``@jitter=D`` adds a seeded uniform extra delay in [0, D] on top of a
  ``latency`` value — WAN jitter, reproducible per seed.

Injection points wired in this codebase:

    store.put / store.get / store.list / store.delete   store/store.py
    store.commit_window          store/store.py group-commit window flush
                                 (``drop`` = force a window split mid-
                                 fill; ``error``/``raise`` = the window's
                                 WAL sync fails — every writer parked on
                                 the window gets a typed 5xx and NONE of
                                 its records commit)
    watch                        store Watch + server/rest.py RestWatch
    watch.evict                  store/store.py Watch._push (``drop`` =
                                 force-evict the watcher as if its
                                 bounded queue overflowed: the stream
                                 ends with a terminal typed 410 and the
                                 informer relists — the backpressure
                                 drill)
    rest.request                 server/rest.py RestClient._request
    syncer.apply                 syncer/engine.py applier pool
    device.step                  syncer/core.py FusedBucket.submit/probe
    cluster.health               reconcilers/cluster pull-mode healthcheck
    admission.chain              admission/chain.py chain entry (writes)
    admission.quota              admission/quota.py post-reservation
                                 (an injected error exercises rollback)
    admission.flow               admission/flow.py FlowController.acquire
    encode.cache                 store/store.py encode-once byte cache
                                 (``drop`` discards a cached entry on
                                 lookup, forcing the re-encode fallback)
    router.proxy                 sharding/router.py router→shard relay
                                 (error = a shard relay answers 503,
                                 latency = a slow shard hop — the chaos
                                 lever for shard-death drills)
    repl.ship                    replication/hub.py WAL feed (error =
                                 the ship stream dies and the follower
                                 reconnects, latency = ship lag)
    repl.apply                   replication/applier.py record apply
                                 (error = the follower drops the feed
                                 and re-resumes from its applied RV)
    repl.promote                 replication/applier.py standby
                                 promotion (error = the promotion
                                 attempt aborts and retries after the
                                 next probe cycle)
    server.drain                 server/server.py graceful drain (error
                                 = the drain aborts and the shutdown
                                 escalates to an immediate hard stop,
                                 latency = a slow drain)
    scenario.phase               scenarios/engine.py phase boundary
                                 (latency = a stalled phase transition,
                                 error = the scenario run aborts — the
                                 harness's own failure path is drilled
                                 like everything else)
    migrate.cutover              sharding/migrate.py between migration
                                 finish and the ring flip — the worst
                                 instant to die (target loaded, ring not
                                 flipped; error = the migration aborts
                                 and the source fence rolls back so the
                                 cluster keeps serving from its old
                                 owner, latency = a slow cutover)
    link.partition               peer-pair link cut (``drop`` +
                                 ``@peer``): every link-aware transport
                                 — RestClient requests, RestWatch
                                 streams, the replication feed, the
                                 applier's probe/ack/fence calls —
                                 raises ConnectionError while the
                                 directed pair is cut
    link.delay                   peer-pair WAN latency (``latency`` +
                                 ``@peer`` [+ ``@jitter``]) at the same
                                 link-aware sites; sync sites sleep,
                                 async sites await
    fleet.solve                  fleet/solver.py device bin-pack entry
                                 (error = the solve fails and the
                                 scheduler retries with its last good
                                 assignment intact, latency = a slow
                                 solve tick)

Sites call the module-level helpers, which are near-free no-ops when no
injector is active (one global read).
"""

from __future__ import annotations

import logging
import os
import random
import threading
from dataclasses import dataclass, field

from .analysis.sanitize import make_lock
from .utils.errors import UnavailableError
from .utils.trace import REGISTRY

log = logging.getLogger(__name__)

ACTIONS = ("error", "raise", "drop", "latency", "poison_row")

#: The injection-point registry — the single spelling authority for every
#: point threaded through the codebase. ``scripts/lint.py``'s
#: fault-point-registry checker cross-references this set against every
#: ``maybe_fail``/``should_drop`` call site (a typo'd point silently
#: never fires) and against the ``point:action`` specs in tests (a point
#: no test exercises is a degraded-mode path with no drill). Add the
#: point here FIRST when wiring a new site.
POINTS = frozenset({
    "store.put",
    "store.get",
    "store.list",
    "store.delete",
    "store.commit_window",
    "watch",
    "watch.evict",
    "rest.request",
    "syncer.apply",
    "device.step",
    "cluster.health",
    "admission.chain",
    "admission.quota",
    "admission.flow",
    "encode.cache",
    "router.proxy",
    "repl.ship",
    "repl.apply",
    "repl.promote",
    "server.drain",
    "scenario.phase",
    "migrate.cutover",
    "link.partition",
    "link.delay",
    "fleet.solve",
})


class InjectedFault(RuntimeError):
    """A deliberately-injected non-API failure (``raise``/``poison_row``)."""


@dataclass
class FaultRule:
    point: str
    action: str
    value: float | None = None
    at_tick: int | None = None
    # link-scoped modifiers (``@peer=SRC>DST`` / ``@heal=N`` / ``@jitter=D``)
    peer: tuple[str, str, bool] | None = None  # (src, dst, bidirectional)
    heal: int | None = None
    jitter: float | None = None
    fired: int = 0

    def matches_peer(self, peer: tuple[str, str] | None) -> bool:
        """Does this rule apply to the (src, dst) directed pair? Rules
        without ``@peer`` fire everywhere; peer-scoped rules only fire at
        link-aware sites that supply the pair."""
        if self.peer is None:
            return True
        if peer is None:
            return False

        def one_way(src_pat: str, dst_pat: str) -> bool:
            return (src_pat in ("*", peer[0])
                    and dst_pat in ("*", peer[1]))

        src_pat, dst_pat, bidir = self.peer
        return one_way(src_pat, dst_pat) or (
            bidir and one_way(dst_pat, src_pat))


def _parse_value(raw: str) -> float:
    if raw.endswith("ms"):
        return float(raw[:-2]) / 1000.0
    if raw.endswith("s"):
        return float(raw[:-1])
    return float(raw)


def parse_spec(spec: str) -> list[FaultRule]:
    rules: list[FaultRule] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        point, _, rest = clause.partition(":")
        if not rest:
            raise ValueError(f"fault clause {clause!r} needs '<point>:<action>'")
        rest, *mods = rest.split("@")
        at_tick: int | None = None
        heal: int | None = None
        jitter: float | None = None
        peer: tuple[str, str, bool] | None = None
        for mod in mods:
            mkey, _, mval = mod.partition("=")
            if mkey == "tick":
                at_tick = int(mval)
            elif mkey == "heal":
                heal = int(mval)
            elif mkey == "jitter":
                jitter = _parse_value(mval)
            elif mkey == "peer":
                bidir = "<>" in mval
                src, _, dst = (mval.partition("<>") if bidir
                               else mval.partition(">"))
                if not src or not dst:
                    raise ValueError(
                        f"bad @peer={mval!r} in {clause!r}: want SRC>DST "
                        f"(directed) or SRC<>DST (both ways); '*' wildcards")
                peer = (src.strip(), dst.strip(), bidir)
            else:
                raise ValueError(f"unknown fault modifier {mod!r} in {clause!r}")
        action, _, raw = rest.partition("=")
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r} in {clause!r} "
                f"(one of {', '.join(ACTIONS)})")
        value = _parse_value(raw) if raw else None
        if jitter is not None and action != "latency":
            raise ValueError(
                f"@jitter only modifies latency rules, not {action!r} "
                f"in {clause!r}")
        rules.append(FaultRule(point.strip(), action, value, at_tick,
                               peer=peer, heal=heal, jitter=jitter))
    return rules


@dataclass
class _PointState:
    rules: list[FaultRule] = field(default_factory=list)
    count: int = 0
    rng: random.Random | None = None


class FaultInjector:
    """A parsed, seeded fault schedule; thread-safe (REST clients and the
    store-pool executor hit points off the serving loop)."""

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._lock = make_lock("faults.injector")
        self._points: dict[str, _PointState] = {}
        for rule in parse_spec(spec):
            st = self._points.setdefault(rule.point, _PointState())
            st.rules.append(rule)
        for point, st in self._points.items():
            # per-point PRNG: a point's schedule depends only on its own
            # invocation sequence, never on interleaving with other points
            st.rng = random.Random(f"{seed}:{point}")

    def describe(self) -> str:
        return f"KCP_FAULTS={self.spec!r} seed={self.seed}"

    # ------------------------------------------------------------ firing

    def _advance(self, point: str, rows=None,
                 peer: tuple[str, str] | None = None
                 ) -> list[tuple[FaultRule, float]]:
        """Advance ``point``'s schedule; returns the fired (rule, delay)
        pairs. ``delay`` is the rule's latency value plus its seeded
        jitter sample (0.0 for non-latency actions)."""
        st = self._points.get(point)
        if st is None:
            return []
        with self._lock:
            st.count += 1
            fired: list[tuple[FaultRule, float]] = []
            for r in st.rules:
                if not r.matches_peer(peer):
                    continue
                if r.heal is not None and st.count >= r.heal:
                    continue  # healed: fires on invocations 1..heal-1
                if r.action == "poison_row":
                    if (rows is not None and r.value is not None
                            and int(r.value) in rows):
                        fired.append((r, 0.0))
                    continue
                if r.at_tick is not None:
                    if st.count == r.at_tick:
                        fired.append((r, 0.0))
                    continue
                if r.action == "latency":
                    delay = (r.value or 0.0) + (
                        st.rng.uniform(0.0, r.jitter) if r.jitter else 0.0)
                    fired.append((r, delay))
                    continue
                p = 1.0 if r.value is None else r.value
                if st.rng.random() < p:
                    fired.append((r, 0.0))
            for r, _ in fired:
                r.fired += 1
        for r, _ in fired:
            REGISTRY.counter(
                "fault_injected_total",
                "faults fired by the KCP_FAULTS injector").inc()
            REGISTRY.counter(
                f"fault_injected_{point.replace('.', '_')}_total",
                f"faults fired at the {point} injection point").inc()
            log.info("fault injected: %s:%s (invocation %d)",
                     point, r.action, st.count)
        return fired

    def maybe_fail(self, point: str, rows=None) -> float:
        """Advance ``point``'s schedule. Raises if an ``error`` (503) /
        ``raise`` / matching ``poison_row`` rule fires; returns the summed
        ``latency`` delay in seconds otherwise (0.0 when quiet)."""
        delay = 0.0
        for r, d in self._advance(point, rows):
            if r.action == "latency":
                delay += d
            elif r.action == "error":
                raise UnavailableError(f"injected fault: {point}:error")
            elif r.action == "raise":
                raise InjectedFault(f"injected fault: {point}:raise")
            elif r.action == "poison_row":
                raise InjectedFault(
                    f"injected fault: {point}:poison_row={int(r.value)}")
        return delay

    def should_drop(self, point: str) -> bool:
        """Advance ``point``'s schedule; True if a ``drop`` rule fired."""
        return any(r.action == "drop" for r, _ in self._advance(point))

    # ----------------------------------------------------- link realism

    def link_cut(self, point: str, src: str, dst: str) -> bool:
        """Advance a link point for the (src, dst) directed pair; True if
        a (possibly peer-scoped) ``drop`` rule cut the link."""
        return any(r.action == "drop"
                   for r, _ in self._advance(point, peer=(src, dst)))

    def link_delay(self, point: str, src: str, dst: str) -> float:
        """Summed latency+jitter seconds injected on the directed pair
        (0.0 when quiet). The caller sleeps — sync sites ``time.sleep``,
        async sites ``await asyncio.sleep``."""
        return sum(d for r, d in self._advance(point, peer=(src, dst))
                   if r.action == "latency")

    def snapshot(self) -> dict[str, int]:
        """point -> invocation count (replay/debugging aid)."""
        with self._lock:
            return {p: st.count for p, st in self._points.items()}


# --------------------------------------------------------------------------
# Process-global injector: KCP_FAULTS env (read once) or install()ed by
# tests / the chaos harness. Sites call the module helpers below.
# --------------------------------------------------------------------------

_ACTIVE: FaultInjector | None = None
_ENV_CHECKED = False


def active() -> FaultInjector | None:
    global _ACTIVE, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get("KCP_FAULTS", "")
        if spec:
            _ACTIVE = FaultInjector(
                spec, int(os.environ.get("KCP_FAULTS_SEED", "0")))
            log.warning("fault injection ACTIVE: %s", _ACTIVE.describe())
    return _ACTIVE


def install(inj: FaultInjector | None) -> None:
    """Activate an injector programmatically (tests, chaos harnesses)."""
    global _ACTIVE, _ENV_CHECKED
    _ACTIVE = inj
    _ENV_CHECKED = True


def clear() -> None:
    install(None)


def maybe_fail(point: str, rows=None) -> float:
    inj = _ACTIVE if _ENV_CHECKED else active()
    return inj.maybe_fail(point, rows) if inj is not None else 0.0


def should_drop(point: str) -> bool:
    inj = _ACTIVE if _ENV_CHECKED else active()
    return inj.should_drop(point) if inj is not None else False


def link_fault(src: str, dst: str) -> float:
    """One call per transport attempt on a directed link: raises
    :class:`ConnectionError` while an active ``link.partition`` rule cuts
    (src, dst); otherwise returns the ``link.delay`` seconds the caller
    must sleep (0.0 when no injector is active). ``dst`` is conventionally
    the target's ``host:port``; feed-side sources use stable role names
    (``repl.feed`` → subscriber role) so specs stay port-free."""
    inj = _ACTIVE if _ENV_CHECKED else active()
    if inj is None:
        return 0.0
    if inj.link_cut("link.partition", src, dst):
        raise ConnectionError(f"injected link partition: {src}>{dst}")
    return inj.link_delay("link.delay", src, dst)
