"""kcp_tpu — a TPU-native multi-tenant control-plane framework.

A brand-new implementation of the capabilities of the kcp prototype
(reference: /root/reference, sttts/kcp @ Oct 2021): a minimal
Kubernetes-style API server serving many cheap *logical clusters* from one
store, schema import and lowest-common-denominator negotiation of CRDs,
label-driven spec<->status syncers, and a multi-cluster workload splitter.

Instead of one goroutine per workspace (reference:
pkg/reconciler/cluster/controller.go:243-263), the per-tenant reconcile
loops are vectorized as batched JAX programs (vmap/pjit/Pallas) behind a
swappable reconciler backend:

- host side (Python/asyncio): API surface, storage, watches, schema trees
- device side (JAX): object diffing, patch-set decisions, replica
  placement, label-selector fan-out, schema hashing

Layout:
- ``kcp_tpu.store``        logical-cluster keyspace + watch hub (etcd analog)
- ``kcp_tpu.apis``         API types: Cluster, APIResourceImport, ...
- ``kcp_tpu.client``       clients, informers, listers (pkg/client analog)
- ``kcp_tpu.reconciler``   controller runtime, batched workqueue, backends
- ``kcp_tpu.ops``          device kernels: encode/diff/placement/labelmatch
- ``kcp_tpu.models``       the flagship fused reconcile-step program
- ``kcp_tpu.parallel``     mesh/sharding over the tenant axis
- ``kcp_tpu.syncer``       spec/status syncers (pkg/syncer analog)
- ``kcp_tpu.schemacompat`` LCD schema negotiation (pkg/schemacompat analog)
- ``kcp_tpu.crdpuller``    discovery -> CRD synthesis (pkg/crdpuller analog)
- ``kcp_tpu.server``       minimal REST+watch API server (pkg/server analog)
- ``kcp_tpu.reconcilers``  domain reconcilers (pkg/reconciler analog)
- ``kcp_tpu.physical``     fake physical-cluster backend (kind analog)
- ``kcp_tpu.cli``          CLI binaries (cmd/ analog)
- ``kcp_tpu.utils``        errors, tracing/profiling, race detection
- ``kcp_tpu.native``       ctypes bindings for the C++ runtime (native/)

The serving core (``kcp_tpu.syncer.core``) fuses every engine's rows and
the deployment splitter's placement into ONE reconcile-step program per
schema bucket per tick — optionally sharded over a (hosts, tenants,
slots) device mesh (``--mesh``), optionally through the Pallas
decide+match kernel (``--pallas``). The server serves TLS by default
with self-generated certs, RBAC-lite with escalation prevention, and
/debug/profile (host sampling profiler) + /debug/trace (XLA) next to
/metrics.
"""

__version__ = "0.1.0"
