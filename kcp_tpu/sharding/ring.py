"""Shard ring: consistent (rendezvous/HRW) hashing of logical clusters.

The sharded control plane partitions by logical-cluster name — the unit
the whole fork is organized around (SURVEY §0: many cheap tenant control
planes keyed by cluster prefix; upstream kcp later shipped the same
partition as shards). Rendezvous hashing (highest-random-weight) gives
the two properties a shard ring needs with no virtual-node bookkeeping:

- deterministic, coordination-free: every router (and every smart
  client) computes the same owner from the shard list alone;
- minimal movement: adding a shard reassigns only the keys whose
  highest weight the new shard now holds (~1/N of the keyspace);
  removing one reassigns only ITS keys.

Weights come from blake2b over ``shard-name \\x00 cluster-name`` — a
stable, process-independent hash (``hash()`` is per-process salted and
would scatter ownership across restarts).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

DEFAULT_SHARDS_ENV = "KCP_SHARDS"


@dataclass(frozen=True)
class Shard:
    """One shard server: a stable identity + its primary's base URL,
    plus any read replicas fed by that primary's WAL (the router routes
    plain reads to them; writes and RV-resumes stay on the primary)."""

    name: str
    url: str
    replicas: tuple[str, ...] = ()


def _weight(shard_name: str, cluster: str) -> int:
    h = hashlib.blake2b(digest_size=8)
    h.update(shard_name.encode("utf-8"))
    h.update(b"\x00")
    h.update(cluster.encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


def owner_name(names, cluster: str) -> str:
    """HRW owner of ``cluster`` given only the ring's shard NAMES.

    Ownership depends on the name set alone (URLs never enter the
    hash), so a shard that knows the ring's names and its own name can
    verify a smart client's direct request without knowing anyone's
    address — the server half of the ``X-Kcp-Ring-Epoch`` handshake."""
    return max(names, key=lambda n: (_weight(n, cluster), n))


class ShardRing:
    """An ordered, deduplicated set of shards with HRW ownership.

    ``overrides`` is the per-cluster *pending-migration* overlay: while
    a cluster's WAL is being streamed to its new HRW owner, the ring
    pins it to the OLD owner by name so ownership flips atomically per
    cluster (when the migration completes and the override is dropped),
    never wholesale at an epoch bump. Overrides ride the ``/ring``
    document, so routers, shards, and smart clients all resolve the
    same owner mid-migration."""

    def __init__(self, shards: list[Shard],
                 overrides: dict[str, str] | None = None):
        if not shards:
            raise ValueError("shard ring needs at least one shard")
        seen_names: dict[str, str] = {}
        seen_urls: dict[str, str] = {}
        for s in shards:
            if s.name in seen_names:
                raise ValueError(
                    f"duplicate shard name {s.name!r} (urls {seen_names[s.name]!r}"
                    f" and {s.url!r}): shard names are ring identities — "
                    f"rename one entry in KCP_SHARDS/--shards")
            if s.url in seen_urls:
                raise ValueError(
                    f"duplicate shard url {s.url!r} (names {seen_urls[s.url]!r}"
                    f" and {s.name!r}): two ring entries would route distinct "
                    f"keyspaces to one server — remove or fix one entry in "
                    f"KCP_SHARDS/--shards")
            seen_names[s.name] = s.url
            seen_urls[s.url] = s.name
        self.shards: tuple[Shard, ...] = tuple(shards)
        ov = dict(overrides or {})
        for cluster, name in ov.items():
            if name not in seen_names:
                raise ValueError(
                    f"override {cluster!r} -> {name!r} names a shard "
                    f"not in the ring ({sorted(seen_names)})")
        self.overrides: dict[str, str] = ov

    def __len__(self) -> int:
        return len(self.shards)

    def __iter__(self):
        return iter(self.shards)

    def index_of(self, name: str) -> int:
        for i, s in enumerate(self.shards):
            if s.name == name:
                return i
        raise ValueError(f"no shard named {name!r} in the ring")

    def owner_index(self, cluster: str) -> int:
        """Index of the shard owning ``cluster`` (ties broken by name so
        the choice is total even for colliding 64-bit weights); a
        pending-migration override pins the cluster to its old owner."""
        pinned = self.overrides.get(cluster)
        if pinned is not None:
            return self.index_of(pinned)
        return self.hrw_index(cluster)

    def hrw_index(self, cluster: str) -> int:
        """Pure HRW owner index, ignoring overrides — the *target* of a
        pending migration (``owner_index`` is where traffic goes NOW)."""
        best = 0
        best_key = (_weight(self.shards[0].name, cluster), self.shards[0].name)
        for i in range(1, len(self.shards)):
            key = (_weight(self.shards[i].name, cluster), self.shards[i].name)
            if key > best_key:
                best, best_key = i, key
        return best

    def owner(self, cluster: str) -> Shard:
        return self.shards[self.owner_index(cluster)]

    def with_shard_added(self, shard: Shard,
                         pin_clusters: list[str] | None = None) -> "ShardRing":
        """A new ring with ``shard`` appended; ``pin_clusters`` are the
        existing clusters whose HRW owner would change — each is pinned
        (override) to its CURRENT owner so nothing moves until its
        migration completes."""
        ov = dict(self.overrides)
        for cluster in pin_clusters or ():
            ov.setdefault(cluster, self.shards[self.owner_index(cluster)].name)
        return ShardRing(list(self.shards) + [shard], ov)

    def with_shard_removed(self, name: str) -> "ShardRing":
        """A new ring without shard ``name``; refuses while any override
        still pins a cluster to it (that cluster's data lives there)."""
        pinned = sorted(c for c, n in self.overrides.items() if n == name)
        if pinned:
            raise ValueError(
                f"cannot remove shard {name!r}: clusters {pinned} are "
                f"still pinned to it by pending migrations")
        remaining = [s for s in self.shards if s.name != name]
        if len(remaining) == len(self.shards):
            raise ValueError(f"no shard named {name!r} in the ring")
        return ShardRing(remaining, dict(self.overrides))

    def without_override(self, cluster: str) -> "ShardRing":
        """A new ring with ``cluster``'s pending-migration pin dropped —
        the atomic per-cluster ownership flip."""
        if cluster not in self.overrides:
            raise ValueError(f"no pending migration for cluster {cluster!r}")
        ov = dict(self.overrides)
        del ov[cluster]
        return ShardRing(list(self.shards), ov)

    @classmethod
    def from_spec(cls, spec: str, replicas: str = "") -> "ShardRing":
        """Parse a shard-list spec: comma-separated ``name=url`` entries
        (bare URLs get ``shard<i>`` names). This is the ``KCP_SHARDS``
        format and the ``kcp start --role router --shards`` argument.

        A shard entry may append ``|``-separated read-replica URLs:
        ``s0=http://h0:6443|http://h0r:6444`` — the first URL is the
        primary (the ring hashes names, so replicas never change
        ownership). ``replicas`` (the ``KCP_REPLICAS`` format) is an
        alternative per-shard mapping, ``;``-separated
        ``name=url[|url...]`` entries, merged after the inline form.
        """
        shards: list[Shard] = []
        for i, entry in enumerate(s.strip() for s in spec.split(",")):
            if not entry:
                continue
            name, sep, url = entry.partition("=")
            if not sep:
                name, url = f"shard{i}", entry
            urls = [u.strip().rstrip("/") for u in url.split("|") if u.strip()]
            if not urls or any("://" not in u for u in urls):
                raise ValueError(
                    f"shard entry {entry!r}: expected "
                    f"[name=]http[s]://host:port[|replica-url...]")
            shards.append(Shard(name.strip(), urls[0], tuple(urls[1:])))
        if replicas:
            by_name = {s.name: s for s in shards}
            for entry in (e.strip() for e in replicas.split(";")):
                if not entry:
                    continue
                name, sep, urls_raw = entry.partition("=")
                name = name.strip()
                if not sep or name not in by_name:
                    raise ValueError(
                        f"replica entry {entry!r}: expected "
                        f"<shard-name>=url[|url...] naming a shard in the "
                        f"ring ({sorted(by_name)})")
                extra = tuple(u.strip().rstrip("/")
                              for u in urls_raw.split("|") if u.strip())
                if any("://" not in u for u in extra):
                    raise ValueError(
                        f"replica entry {entry!r}: URLs must be "
                        f"http[s]://host:port")
                s = by_name[name]
                by_name[name] = Shard(s.name, s.url, s.replicas + extra)
            shards = [by_name[s.name] for s in shards]
        return cls(shards)

    @classmethod
    def from_env(cls) -> "ShardRing":
        spec = os.environ.get(DEFAULT_SHARDS_ENV, "")
        if not spec:
            raise ValueError(
                f"no shard list: set {DEFAULT_SHARDS_ENV} or pass --shards")
        return cls.from_spec(spec, os.environ.get("KCP_REPLICAS", ""))
