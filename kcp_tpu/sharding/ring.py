"""Shard ring: consistent (rendezvous/HRW) hashing of logical clusters.

The sharded control plane partitions by logical-cluster name — the unit
the whole fork is organized around (SURVEY §0: many cheap tenant control
planes keyed by cluster prefix; upstream kcp later shipped the same
partition as shards). Rendezvous hashing (highest-random-weight) gives
the two properties a shard ring needs with no virtual-node bookkeeping:

- deterministic, coordination-free: every router (and every smart
  client) computes the same owner from the shard list alone;
- minimal movement: adding a shard reassigns only the keys whose
  highest weight the new shard now holds (~1/N of the keyspace);
  removing one reassigns only ITS keys.

Weights come from blake2b over ``shard-name \\x00 cluster-name`` — a
stable, process-independent hash (``hash()`` is per-process salted and
would scatter ownership across restarts).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

DEFAULT_SHARDS_ENV = "KCP_SHARDS"


@dataclass(frozen=True)
class Shard:
    """One shard server: a stable identity + its base URL."""

    name: str
    url: str


def _weight(shard_name: str, cluster: str) -> int:
    h = hashlib.blake2b(digest_size=8)
    h.update(shard_name.encode("utf-8"))
    h.update(b"\x00")
    h.update(cluster.encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


class ShardRing:
    """An ordered, deduplicated set of shards with HRW ownership."""

    def __init__(self, shards: list[Shard]):
        if not shards:
            raise ValueError("shard ring needs at least one shard")
        seen: set[str] = set()
        for s in shards:
            if s.name in seen:
                raise ValueError(f"duplicate shard name {s.name!r}")
            seen.add(s.name)
        self.shards: tuple[Shard, ...] = tuple(shards)

    def __len__(self) -> int:
        return len(self.shards)

    def __iter__(self):
        return iter(self.shards)

    def owner_index(self, cluster: str) -> int:
        """Index of the shard owning ``cluster`` (ties broken by name so
        the choice is total even for colliding 64-bit weights)."""
        best = 0
        best_key = (_weight(self.shards[0].name, cluster), self.shards[0].name)
        for i in range(1, len(self.shards)):
            key = (_weight(self.shards[i].name, cluster), self.shards[i].name)
            if key > best_key:
                best, best_key = i, key
        return best

    def owner(self, cluster: str) -> Shard:
        return self.shards[self.owner_index(cluster)]

    @classmethod
    def from_spec(cls, spec: str) -> "ShardRing":
        """Parse a shard-list spec: comma-separated ``name=url`` entries
        (bare URLs get ``shard<i>`` names). This is the ``KCP_SHARDS``
        format and the ``kcp start --role router --shards`` argument."""
        shards: list[Shard] = []
        for i, entry in enumerate(s.strip() for s in spec.split(",")):
            if not entry:
                continue
            name, sep, url = entry.partition("=")
            if not sep:
                name, url = f"shard{i}", entry
            if "://" not in url:
                raise ValueError(
                    f"shard entry {entry!r}: expected [name=]http[s]://host:port")
            shards.append(Shard(name.strip(), url.strip().rstrip("/")))
        return cls(shards)

    @classmethod
    def from_env(cls) -> "ShardRing":
        spec = os.environ.get(DEFAULT_SHARDS_ENV, "")
        if not spec:
            raise ValueError(
                f"no shard list: set {DEFAULT_SHARDS_ENV} or pass --shards")
        return cls.from_spec(spec)
