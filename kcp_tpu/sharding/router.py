"""Scatter-gather router: the frontend of the sharded control plane.

``kcp start --role router --shards s0=http://...,s1=http://...`` serves
the SAME REST surface as a shard, but owns no storage: every request is
routed over the :class:`~kcp_tpu.sharding.ring.ShardRing`.

- **Single-cluster requests** (the overwhelming majority: every tenant
  client, every informer bound to its own workspace) proxy straight
  through to the owning shard — the raw request target and body go over
  the wire verbatim and the shard's response bytes come back verbatim,
  so PR 5's encode-once bytes are relayed without a single re-encode.
  Transport uses :class:`~kcp_tpu.store.remote.ConnectionPool` (bounded
  kept-alive RestClients per shard, one shared per-peer
  :class:`~kcp_tpu.utils.circuit.CircuitBreaker`): a dead shard trips
  once and fails fast 503 instead of stacking 30 s connect timeouts.
- **Wildcard lists** scatter to every shard and merge by byte-splicing
  the shards' ``items`` arrays into one envelope — per-object bytes are
  exactly what the owning shard serialized. The merged list's
  ``resourceVersion`` is a **vector RV** (:mod:`.rvmap`): the per-shard
  RVs packed into one opaque integer.
- **Wildcard watches** merge N per-shard streams. Event lines relay
  byte-verbatim; the router parses each line only to keep per-shard
  position (vector-RV bookkeeping). A resume (``?resourceVersion=``)
  decodes the vector and resumes each shard from ITS OWN honest
  ``since_rv``; a non-vector RV answers 410 Gone (re-list — never
  guess). Shard-local BOOKMARKs are absorbed into the position map;
  client-facing BOOKMARKs carry the vector. A shard stream dying ends
  the merged stream with a terminal in-stream 410 Status — the PR 2
  fault discipline: clients re-list and resume from a fresh vector.
- **Wildcard writes** route through the one copy of the wildcard rule
  (:func:`~kcp_tpu.utils.routing.resolve_write_cluster`) and then the
  ring; a write without ``metadata.clusterName`` is a 400, exactly as
  on a shard.

``router.proxy`` is a KCP_FAULTS injection point (error/latency on the
relay path). Metrics: ``router_proxy_seconds``,
``router_scatter_fanout``, ``router_shard_unavailable_total``,
``router_watch_resumes_total``.
"""

from __future__ import annotations

import asyncio
import base64
import http.client
import json
import logging
import os
import re
import time
from concurrent.futures import ThreadPoolExecutor
from urllib.parse import quote, urlencode, urlsplit

from .. import obs
from ..analysis.sanitize import make_lock
from ..faults import maybe_fail
from ..server.handler import CLUSTER_HEADER, DEFAULT_CLUSTER, _error_response, _status_body
from ..server.httpd import Request, Response, StreamResponse
from ..server.rest import RestWatch
from ..store.remote import ConnectionPool
from ..store.store import WILDCARD, encode_continue
from ..utils import errors
from ..utils.routing import resolve_write_cluster
from ..utils.trace import REGISTRY
from .ring import ShardRing, owner_name
from .rvmap import decode_rvmap, encode_rvmap

log = logging.getLogger(__name__)

_ITEMS_MARKER = b'"items": ['
_RV_RE = re.compile(rb'"resourceVersion": "(\d+)"')
_CONT_RE = re.compile(rb'"continue": "([^"]*)"')


def _encode_router_continue(rvs: list[int], toks: list) -> str:
    """Pack every shard's pinned RV and per-shard store continue token
    into ONE opaque client token — the paged analogue of the vector RV."""
    raw = json.dumps({"v": 1, "n": len(rvs), "r": rvs, "t": toks},
                     separators=(",", ":")).encode()
    return base64.urlsafe_b64encode(raw).decode()


def _decode_router_continue(token: str, n: int):
    """``(rvs, toks)`` or None when the token is malformed or was minted
    against a different shard topology (callers answer typed 410)."""
    try:
        d = json.loads(base64.urlsafe_b64decode(token.encode()))
        if d.get("v") != 1 or d.get("n") != n:
            return None
        rvs, toks = d["r"], d["t"]
        if len(rvs) != n or len(toks) != n:
            return None
        if not all(t is None or isinstance(t, str) for t in toks):
            return None
        return [int(x) for x in rvs], list(toks)
    except (ValueError, KeyError, TypeError):
        return None


def _swap_continue(target: str, token: str) -> str:
    """The original request target with its ``continue`` query value
    replaced by a shard-local token (limit/labelSelector relay as-is)."""
    path, _sep, query = target.partition("?")
    parts = [p for p in query.split("&")
             if p and not p.startswith("continue=")]
    parts.append("continue=" + quote(token, safe=""))
    return path + "?" + "&".join(parts)
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s+(.*)$")


def _merge_expositions(parts: list[tuple[str, str]]) -> str:
    """Merge per-process Prometheus expositions into one page: every
    sample line gains a ``shard="<label>"`` label (appended after any
    existing labels), HELP/TYPE are emitted once per metric (first
    source wins), and metrics group together so the page stays valid
    exposition format (one TYPE per family)."""
    meta: dict[str, list[str]] = {}
    samples: dict[str, list[str]] = {}
    order: list[str] = []
    for label, text in parts:
        esc = label.replace("\\", "\\\\").replace('"', '\\"')
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                name = line.split(None, 3)[2]
                if name not in meta:
                    meta[name] = []
                    order.append(name)
                if not any(ln.split(None, 3)[1] == line.split(None, 3)[1]
                           for ln in meta[name]):
                    meta[name].append(line)
                continue
            if line.startswith("#"):
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            name, _braces, labels, value = m.groups()
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[:-len(suffix)] in meta:
                    family = name[:-len(suffix)]
                    break
            new_labels = (f'{labels},shard="{esc}"' if labels
                          else f'shard="{esc}"')
            if family not in samples and family not in meta:
                order.append(family)
            samples.setdefault(family, []).append(
                f"{name}{{{new_labels}}} {value}")
    out: list[str] = []
    for name in order:
        out.extend(meta.get(name, ()))
        out.extend(samples.get(name, ()))
    return "\n".join(out) + "\n"


class _TapWatch(RestWatch):
    """A per-shard watch stream that keeps each event line's RAW bytes.

    The router relays lines verbatim (zero re-encode — the whole point
    of riding the shards' encode-once serving) while parsing each line
    once for vector-RV bookkeeping. Queue items are ``(raw, msg)``
    pairs; the ``None`` sentinel still marks end-of-stream, and
    ``self.error`` still carries a non-2xx upstream response.
    """

    def _feed(self, chunk: bytes) -> None:
        lines = (self._buf + self._decoder.decode(chunk)).split("\n")
        self._buf = lines.pop()
        for line in lines:
            if line.strip():
                self._events.put_nowait(
                    (line.encode("utf-8") + b"\n", json.loads(line)))

    async def next(self) -> tuple[bytes, dict] | None:
        """Next ``(raw_line, parsed)`` pair, or None at end-of-stream."""
        self._ensure_started()
        if self._closed and self._events.empty():
            return None
        item = await self._events.get()
        if item is None:
            self._events.put_nowait(None)
            return None
        return item

    def drain_raw(self) -> list[tuple[bytes, dict]]:
        out: list[tuple[bytes, dict]] = []
        while not self._events.empty():
            item = self._events.get_nowait()
            if item is None:
                self._events.put_nowait(None)
                break
            out.append(item)
        return out


class RouterHandler:
    """Routes parsed HTTP requests onto a shard ring (no local store)."""

    def __init__(self, ring: ShardRing, version_info: dict | None = None,
                 token: str = "", ca_data: bytes | str | None = None,
                 ca_file: str | None = None, pool_cap: int | None = None,
                 bookmark_every: float | None = None):
        self.ring = ring
        self.version_info = version_info or {
            "major": "0", "minor": "1", "gitVersion": "kcp-tpu-v0.1.0",
            "role": "router", "shards": len(ring)}
        self.ready = False
        cap = pool_cap if pool_cap is not None else int(
            os.environ.get("KCP_ROUTER_POOL", "8"))
        self.bookmark_every = bookmark_every if bookmark_every is not None \
            else float(os.environ.get("KCP_ROUTER_BOOKMARK_S", "5"))
        # router → shard auth: the CLIENT's bearer token is forwarded
        # when present (shards terminate authz; the router stays a dumb
        # pipe), `token` is the fallback credential for routerless
        # callers (health scatters)
        self._pools = [ConnectionPool(s.url, token=token, ca_data=ca_data,
                                      ca_file=ca_file, cap=cap)
                       for s in ring]
        # read replicas per shard (Shard.replicas — WAL-fed followers):
        # plain single-cluster reads round-robin over them, writes and
        # RV-resumes stay on the primary (a replica's applied RV may
        # trail; it answers an honest 410 for resumes beyond it, and the
        # router never manufactures freshness on its behalf)
        self._rpools = [
            [ConnectionPool(url, token=token, ca_data=ca_data,
                            ca_file=ca_file, cap=cap) for url in s.replicas]
            for s in ring]
        self._rr = [0] * len(ring)
        # scatter concurrency: every shard must be reachable in parallel
        # or a wildcard fan-out serializes on the slowest round trip
        self._exec = ThreadPoolExecutor(
            max_workers=max(8, 2 * len(ring)),
            thread_name_prefix="router-io")
        self._proxy_seconds = REGISTRY.histogram(
            "router_proxy_seconds", "one router→shard relay round trip")
        self._fanout = REGISTRY.histogram(
            "router_scatter_fanout", "shards touched per scatter-gather")
        self._unavailable = REGISTRY.counter(
            "router_shard_unavailable_total",
            "relay attempts that found a shard unreachable (transport "
            "failure or open circuit breaker)")
        self._resumes = REGISTRY.counter(
            "router_watch_resumes_total",
            "merged wildcard watches resumed from a decoded vector RV")
        self._replica_reads = REGISTRY.counter(
            "router_replica_reads_total",
            "single-cluster reads served by a shard's read replica")
        self._replica_fallback = REGISTRY.counter(
            "router_replica_fallback_total",
            "replica reads that fell back to the primary (replica "
            "unreachable or refusing)")
        self._watch_spread = REGISTRY.counter(
            "router_watch_spread_total",
            "fresh single-cluster watch streams the router spread onto "
            "a shard's read replica (watch connection capacity scaling "
            "with replica count)")
        # promotion discovery: repeated 503/unreachable answers from a
        # shard's primary trigger a probe of the shard's replica list;
        # a replica answering /replication/status as role=primary is the
        # promoted standby — write routing swaps onto it in place, no
        # router restart. State is touched from executor threads, so the
        # counters/probe clock sit behind a lock (pool swaps themselves
        # are atomic whole-list/whole-slot assignments).
        self._rehomes = REGISTRY.counter(
            "router_rehome_total",
            "times the router swapped a shard's write routing onto a "
            "promoted replica after its primary died or was fenced")
        self._rehome_lock = make_lock("router.rehome")
        self._primary_fails = [0] * len(ring)
        self._last_probe = [0.0] * len(ring)
        self._retired: list[ConnectionPool] = []
        # ring epoch: bumped on every ring change (POST /ring republish,
        # promotion rehome) — smart clients stamp it on direct requests
        # and re-fetch GET /ring when anything disagrees
        self.ring_epoch = 1
        # pool construction knobs, kept for set_ring rebuilds
        self._pool_kw = dict(token=token, ca_data=ca_data, ca_file=ca_file,
                             cap=cap)
        self._raw_chunks = REGISTRY.counter(
            "router_raw_relay_chunks_total",
            "watch-stream chunks forwarded by the zero-parse single-"
            "cluster relay (length-delimited framing only — payload "
            "bytes never decoded, split, or parsed)")

    def close(self) -> None:
        self._exec.shutdown(wait=False, cancel_futures=True)
        for p in self._pools:
            p.close()
        for rp in self._rpools:
            for p in rp:
                p.close()
        for p in self._retired:
            p.close()

    # ------------------------------------------------------------ /ring

    def _ring_doc(self) -> dict:
        """The smart-client handshake document: the current ring and its
        epoch — everything a client needs to compute HRW owners locally
        and go direct."""
        return {
            "epoch": self.ring_epoch,
            "shards": [{"name": s.name, "url": s.url,
                        "replicas": list(s.replicas)}
                       for s in self.ring.shards],
            # pending-migration overlay: clusters pinned to their OLD
            # owner while their data streams to the new one — clients
            # and shards resolve owners override-first, so ownership
            # flips atomically per cluster when its pin drops
            "overrides": dict(self.ring.overrides),
        }

    def set_ring(self, ring: ShardRing) -> None:
        """Swap the serving ring in place (the ``POST /ring`` republish
        after a shard moves to a new address): pools for unchanged URLs
        carry over, pools for departed URLs retire (closed at
        handler.close — in-flight relays may still hold their clients),
        and the ring epoch bumps so smart clients re-fetch."""
        with self._rehome_lock:
            old_by_url = {p.base_url: p for p in self._pools}
            old_r_by_url = {p.base_url: p
                            for rp in self._rpools for p in rp}
            pools: list[ConnectionPool] = []
            rpools: list[list[ConnectionPool]] = []
            for s in ring:
                pools.append(old_by_url.pop(s.url, None)
                             or ConnectionPool(s.url, **self._pool_kw))
                rp = []
                for url in s.replicas:
                    rp.append(old_r_by_url.pop(url, None)
                              or ConnectionPool(url, **self._pool_kw))
                rpools.append(rp)
            self._retired.extend(old_by_url.values())
            self._retired.extend(
                p for p in old_r_by_url.values()
                if all(p not in rp for rp in rpools))
            # whole-slot assignments: concurrent relays hold consistent
            # snapshots of the old lists
            self.ring = ring
            self._pools = pools
            self._rpools = rpools
            self._rr = [0] * len(ring)
            self._primary_fails = [0] * len(ring)
            self._last_probe = [0.0] * len(ring)
            self.ring_epoch += 1
        log.warning("ring republished (epoch %d): %s overrides=%s",
                    self.ring_epoch,
                    [f"{s.name}={s.url}" for s in ring], ring.overrides)
        self._fanout_ring()

    def _fanout_ring(self) -> None:
        """Install the new ring identity (names, epoch, overrides) on
        every member shard, best-effort in the background: a shard that
        misses the fan-out answers spurious ring-mismatch 410s to direct
        clients, who fall back through the router — correctness never
        depends on delivery (shard-side epoch monotonicity discards any
        late, superseded install)."""
        with self._rehome_lock:
            doc = {"epoch": self.ring_epoch,
                   "names": [s.name for s in self.ring.shards],
                   "overrides": dict(self.ring.overrides)}
            pools = list(self._pools)
        payload = json.dumps(doc).encode()

        def _post(pool: ConnectionPool) -> None:
            try:
                with pool.client() as c:
                    c.request_raw("POST", "/ring", payload,
                                  {"content-type": "application/json"})
            except Exception:
                pass  # best-effort (see docstring)

        for p in pools:
            self._exec.submit(_post, p)

    # ----------------------------------------------------------- plumbing

    def _shard_call(self, idx: int, method: str, target: str,
                    payload: bytes | None, headers: dict[str, str],
                    pool: ConnectionPool | None = None, who: str = "",
                    _rehomed: bool = False,
                    ) -> tuple[int, dict[str, str], bytes]:
        """One raw relay round trip to shard ``idx`` (executor thread);
        ``pool`` overrides the primary pool for replica-routed reads.
        Primary relays that fail 503/unreachable feed the promotion-
        discovery counter; when discovery swaps routing onto a promoted
        replica the call retries ONCE against the new primary."""
        delay = maybe_fail("router.proxy")
        if delay:
            time.sleep(delay)
        primary_call = pool is None
        use = self._pools[idx] if primary_call else pool
        t0 = time.perf_counter()
        try:
            with use.client() as c:
                status, rheaders, body = c.request_raw(
                    method, target, payload, headers)
        except errors.UnavailableError:
            # breaker fail-fast: already the right type, just count it
            self._unavailable.inc()
            if primary_call and not _rehomed \
                    and self._note_primary_failure(idx):
                return self._shard_call(idx, method, target, payload,
                                        headers, None, who, _rehomed=True)
            raise
        except (ConnectionError, OSError, TimeoutError,
                http.client.HTTPException) as e:
            self._unavailable.inc()
            if primary_call and not _rehomed \
                    and self._note_primary_failure(idx):
                return self._shard_call(idx, method, target, payload,
                                        headers, None, who, _rehomed=True)
            raise errors.UnavailableError(
                f"shard {who or self.ring.shards[idx].name} "
                f"unreachable: {e}") from e
        finally:
            self._proxy_seconds.observe(time.perf_counter() - t0)
        if primary_call:
            if status == 503:
                # a fenced / mid-promotion ex-primary ANSWERS but refuses
                # (store read-only 503): that is a dead write endpoint
                # for discovery purposes, even though transport is up
                if not _rehomed and self._note_primary_failure(idx):
                    return self._shard_call(idx, method, target, payload,
                                            headers, None, who,
                                            _rehomed=True)
            else:
                with self._rehome_lock:
                    self._primary_fails[idx] = 0
        return status, rheaders, body

    def _note_primary_failure(self, idx: int) -> bool:
        """Count a consecutive primary-relay failure for shard ``idx``;
        at the threshold, probe the shard's replicas for a promoted
        primary (``/replication/status`` role=primary, unfenced) and
        swap write routing onto it in place. Returns True when routing
        changed — the caller retries once against the new primary."""
        now = time.monotonic()
        with self._rehome_lock:
            self._primary_fails[idx] += 1
            if self._primary_fails[idx] < 2 or not self._rpools[idx]:
                return False
            if now - self._last_probe[idx] < 0.25:
                return False  # probe at most ~4x/s per shard
            self._last_probe[idx] = now
            candidates = list(self._rpools[idx])
        promoted = None
        for p in candidates:
            info = self._probe_status(p)
            if (info is not None and not info.get("fenced")
                    and info.get("role") == "primary"):
                promoted = p
                break
        if promoted is None:
            return False
        with self._rehome_lock:
            if self._pools[idx] is promoted:
                return True  # another thread already swapped
            old = self._pools[idx]
            # whole-slot / whole-list assignments: concurrent readers
            # hold snapshots of the old list and stay consistent
            self._pools[idx] = promoted
            self._rpools[idx] = [p for p in self._rpools[idx]
                                 if p is not promoted]
            # the dead primary pool is retired, not closed: in-flight
            # calls may still hold its clients (closed at handler.close)
            self._retired.append(old)
            self._primary_fails[idx] = 0
            # the ring itself re-points at the promoted primary and the
            # epoch bumps: smart clients going direct to the dead URL
            # fall back once, re-fetch /ring, and follow the promotion
            s = self.ring.shards[idx]
            shards = list(self.ring.shards)
            shards[idx] = type(s)(
                s.name, promoted.base_url,
                tuple(u for u in s.replicas if u != promoted.base_url))
            self.ring = ShardRing(shards, dict(self.ring.overrides))
            self.ring_epoch += 1
        self._rehomes.inc()
        log.warning("shard %s: write routing re-homed %s -> %s "
                    "(promoted replica)", self.ring.shards[idx].name,
                    old.base_url, promoted.base_url)
        self._fanout_ring()
        return True

    @staticmethod
    def _probe_status(pool: ConnectionPool) -> dict | None:
        """GET /replication/status through ``pool``; None when the
        endpoint is unreachable or not a replication participant."""
        try:
            with pool.client() as c:
                status, _h, body = c.request_raw("GET",
                                                 "/replication/status")
            if status != 200:
                return None
            out = json.loads(body)
            return out if isinstance(out, dict) else None
        except Exception:  # noqa: BLE001 — a failed probe is "not promoted"
            return None

    async def _call(self, idx: int, method: str, target: str,
                    payload: bytes | None, headers: dict[str, str],
                    pool: ConnectionPool | None = None, who: str = ""):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, self._shard_call, idx, method, target, payload,
            headers, pool, who)

    async def _read_via_replica(self, idx: int, target: str,
                                req: Request) -> Response:
        """A single-cluster read, round-robined over the owning shard's
        replicas; primary fallback when every replica is unreachable or
        refusing. RV-carrying reads (X-Kcp-Min-Rv, continue tokens,
        resourceVersion params) forward as-is — the replica's RV
        barrier parks them until its applied RV covers the pin, so
        they no longer burn the primary. Fallbacks are metered per
        reason: 503 is the replica's lag shed, 504 its RV-barrier
        timeout, transport/breaker failures mean it was unreachable."""
        pools = self._rpools[idx]
        n = len(pools)
        start = self._rr[idx] % n
        self._rr[idx] = (start + 1) % n
        headers = self._fwd_headers(req)
        reasons: set[str] = set()
        for k in range(n):
            j = (start + k) % n
            who = f"{self.ring.shards[idx].name}/replica{j}"
            try:
                status, h, body = await self._call(
                    idx, "GET", target, None, headers,
                    pool=pools[j], who=who)
            except errors.UnavailableError:
                reasons.add("breaker_open")
                continue
            if status == 503:
                reasons.add("lag_shed")
                continue
            if status == 504:
                # the replica is healthy but behind the read's required
                # RV and the bounded wait expired: the next replica may
                # be caught up; otherwise the primary answers
                reasons.add("consistent_timeout")
                continue
            self._replica_reads.inc()
            return self._relay(status, h, body)
        self._replica_fallback.inc()
        for r in ("consistent_timeout", "lag_shed", "breaker_open"):
            if r in reasons:
                REGISTRY.counter(
                    f"router_replica_fallback_{r}_total").inc()
                break
        status, h, body = await self._call(idx, "GET", target, None, headers)
        return self._relay(status, h, body)

    def _replica_watch_pool(self, idx: int,
                            req: Request) -> ConnectionPool | None:
        """Where a single-cluster watch stream lands: round-robin
        across the shard's primary AND its replicas, so live watch
        connection count scales with the replica count — a replica's
        stream is its own honest RV sequence. Resumes used to pin to
        the primary (a lagging replica answered 410 beyond its applied
        RV); with the consistent-read gate a replica parks the resume
        until its applied RV covers it, so RV-resumes spread too —
        reject_future_rv still answers the typed 410 if the bounded
        wait expires."""
        pools = self._rpools[idx]
        if not pools:
            return None
        j = self._rr[idx] % (len(pools) + 1)
        self._rr[idx] = (j + 1) % (len(pools) + 1)
        if j == len(pools):
            return None  # the primary's turn in the rotation
        self._watch_spread.inc()
        return pools[j]

    async def _scatter(self, method: str, target: str,
                       headers: dict[str, str]):
        """The same request against every shard, in parallel. Raises
        UnavailableError if ANY shard is unreachable — a partial scatter
        cannot honestly claim cross-shard answers."""
        self._fanout.observe(len(self.ring))
        return await asyncio.gather(
            *(self._call(i, method, target, None, headers)
              for i in range(len(self.ring))))

    @staticmethod
    def _fwd_headers(req: Request) -> dict[str, str]:
        h = {}
        for k, out in (("authorization", "Authorization"),
                       ("content-type", "Content-Type"),
                       ("accept", "Accept"),
                       # session read-your-writes floor: the replica's
                       # RV barrier needs the client's required RV
                       ("x-kcp-min-rv", "X-Kcp-Min-Rv")):
            v = req.headers.get(k)
            if v:
                h[out] = v
        # trace propagation: the shard's server span parents onto the
        # router's relay span (the current context installed by __call__)
        ctx = obs.current()
        if ctx is not None:
            h[obs.TRACEPARENT] = ctx.header()
        return h

    @staticmethod
    def _relay(status: int, rheaders: dict[str, str], body: bytes) -> Response:
        lower = {k.lower(): v for k, v in rheaders.items()}
        resp = Response(status=status, body=body,
                        content_type=lower.get("content-type",
                                               "application/json"))
        if "retry-after" in lower:
            resp.headers["Retry-After"] = lower["retry-after"]
        if "x-kcp-ring-epoch" in lower:
            # a shard's ring-mismatch stamp passes through untouched:
            # a routed-but-smart-aware client sees the same staleness
            # signal it would on the direct path
            resp.headers["X-Kcp-Ring-Epoch"] = lower["x-kcp-ring-epoch"]
        if "x-kcp-rv" in lower:
            # a write's committed RV: routed clients raise their session
            # read-your-writes floor from it exactly like direct ones
            resp.headers["X-Kcp-Rv"] = lower["x-kcp-rv"]
        return resp

    @staticmethod
    def _parse_resource(segs: list[str]):
        """Path-shape parse (no scheme resolution — shards resolve):
        ``(group, version, namespace, resource, name, subresource)`` or
        None for discovery / non-resource paths."""
        if segs[0] == "api":
            group, rest = "", segs[1:]
        elif segs[0] == "apis":
            if len(segs) < 3:
                return None
            group, rest = segs[1], segs[2:]
        else:
            return None
        if len(rest) < 2:
            return None  # /api/v1 and /apis/g/v are discovery
        _version, rest = rest[0], rest[1:]
        namespace = ""
        if rest[0] == "namespaces" and len(rest) >= 3:
            namespace, rest = rest[1], rest[2:]
        resource, rest = rest[0], rest[1:]
        name = rest[0] if rest else None
        sub = rest[1] if len(rest) > 1 else None
        if len(rest) > 2:
            return None
        return (group, _version, namespace, resource, name, sub)

    # ------------------------------------------------------------ routing

    async def __call__(self, req: Request) -> Response | StreamResponse:
        """Route one request under a trace context: the client's
        ``traceparent`` is honored (else a head-sampled root is minted),
        the relay span covers the whole routing decision + shard round
        trip(s), and ``_fwd_headers`` hands every shard hop the relay
        span as its parent. SLO-breaching relays force-record."""
        tracer = obs.TRACER
        if not tracer.enabled:
            return await self._route(req)
        ctx = tracer.from_headers(req.headers)
        if ctx is None and tracer.head_sampled():
            ctx = tracer.mint(sampled=True)
        if ctx is None or not ctx.sampled:
            # unsampled fast path (the shard makes no decision of its
            # own: no traceparent is forwarded, and its own coin stays
            # in its pocket for direct traffic); SLO upgrade after the
            # fact, mirroring the shard handler
            t0 = time.time()
            resp = await self._route(req)
            dur = time.time() - t0
            if dur >= tracer.slo_s:
                base = ctx or tracer.mint(sampled=False)
                if base is not None:
                    sub = tracer.child(base)
                    obs.record_span(
                        "router.relay", sub, base.span_id, t0, dur,
                        {"method": req.method, "path": req.path,
                         "status": getattr(resp, "status", 200),
                         "slo_breach": True}, force=True)
            return resp
        sub = tracer.child(ctx)
        token = obs.set_current(sub)
        t0 = time.time()
        status = 500
        try:
            resp = await self._route(req)
            status = getattr(resp, "status", 200)
            return resp
        finally:
            obs.reset_current(token)
            dur = time.time() - t0
            attrs = {"method": req.method, "path": req.path,
                     "status": status}
            if dur >= tracer.slo_s:
                attrs["slo_breach"] = True
            obs.record_span("router.relay", sub, ctx.span_id, t0, dur,
                            attrs)

    async def _route(self, req: Request) -> Response | StreamResponse:
        segs = [s for s in req.path.split("/") if s]
        cluster = req.headers.get(CLUSTER_HEADER, DEFAULT_CLUSTER)
        cluster_in_path = False
        if len(segs) >= 2 and segs[0] == "clusters":
            cluster = segs[1]
            segs = segs[2:]
            cluster_in_path = True
        if not segs:
            return Response.of_json(
                {"paths": ["/api", "/apis", "/healthz", "/version"]})
        head = segs[0]
        if head in ("healthz", "livez"):
            return Response(body=b"ok", content_type="text/plain")
        if head == "readyz":
            if self.ready:
                return Response(body=b"ok", content_type="text/plain")
            return Response(status=500, body=b"not ready",
                            content_type="text/plain")
        if head == "ring":
            # the smart-client handshake surface: GET serves the current
            # ring + epoch; POST republishes it — {"shards": ...} swaps
            # the whole ring (the operator/driver move after a shard
            # restarts on a new address), {"add"}/{"complete"}/{"remove"}
            # are the elastic scale-out lifecycle (sharding/migrate.py)
            if req.method == "GET":
                return Response.of_json(self._ring_doc())
            if req.method == "POST":
                try:
                    body = json.loads(req.body) if req.body else {}
                except ValueError as e:
                    return _error_response(errors.BadRequestError(
                        f"malformed JSON body: {e}"))
                if not isinstance(body, dict):
                    return _error_response(errors.BadRequestError(
                        "body must be a JSON object"))
                try:
                    if "add" in body:
                        return await self._ring_add(req, body["add"])
                    if "complete" in body:
                        ring = self.ring.without_override(
                            str(body["complete"]))
                        self.set_ring(ring)
                        return Response.of_json(self._ring_doc())
                    if "remove" in body:
                        ring = self.ring.with_shard_removed(
                            str(body["remove"]))
                        self.set_ring(ring)
                        return Response.of_json(self._ring_doc())
                    spec = body.get("shards", "")
                    if isinstance(spec, list):
                        spec = ",".join(
                            f"{s['name']}={s['url']}"
                            + "".join("|" + r
                                      for r in s.get("replicas", ()))
                            for s in spec)
                    parsed = ShardRing.from_spec(spec)
                    # a full republish keeps pending-migration pins whose
                    # shards survived: a shard moving addresses mid-
                    # migration must not silently flip pinned ownership
                    keep = {s.name for s in parsed.shards}
                    ring = ShardRing(
                        list(parsed.shards),
                        {c: n for c, n in self.ring.overrides.items()
                         if n in keep})
                except errors.ApiError as e:
                    return _error_response(e)
                except (ValueError, KeyError, TypeError) as e:
                    return _error_response(errors.BadRequestError(
                        f"malformed ring spec: {e}"))
                self.set_ring(ring)
                return Response.of_json(self._ring_doc())
            return _error_response(errors.BadRequestError(
                f"unsupported method {req.method} for /ring"))
        if head == "metrics":
            if req.param("fleet") in ("1", "true"):
                return await self._metrics_fleet(req)
            return Response(body=REGISTRY.expose().encode("utf-8"),
                            content_type="text/plain; version=0.0.4")
        if head == "debug" and segs[1:] == ["trace"] and (
                req.param("id") or req.param("slowest")):
            return await self._trace_scatter(req)
        try:
            if head == "version":
                return await self._version(req)
            if head == "clusters" and len(segs) == 1:
                return await self._clusters(req)
            # everything below is cluster-scoped: normalize the cluster
            # into the forwarded target so shards never see our header
            target = req.target if cluster_in_path else (
                "/clusters/" + quote(cluster, safe="*") + req.target)
            shape = self._parse_resource(segs)
            is_watch = (req.method == "GET" and shape is not None
                        and shape[4] is None
                        and req.param("watch") in ("true", "1"))
            if cluster != WILDCARD:
                idx = self.ring.owner_index(cluster)
                if is_watch:
                    return self._stream_proxy(
                        idx, target, req,
                        pool=self._replica_watch_pool(idx, req))
                if (req.method == "GET" and self._rpools[idx]
                        and shape is not None):
                    # RV-carrying reads (min-RV stamps, RV-pinned
                    # continue tokens, resourceVersion params) go to
                    # replicas too: the replica's RV barrier holds the
                    # read until its applied RV covers the pin
                    return await self._read_via_replica(idx, target, req)
                status, h, body = await self._call(
                    idx, req.method, target, req.body or None,
                    self._fwd_headers(req))
                return self._relay(status, h, body)
            return await self._wildcard(req, segs, shape, is_watch, target)
        except errors.ApiError as e:
            return _error_response(e)

    # ----------------------------------------------------------- wildcard

    async def _wildcard(self, req: Request, segs: list[str], shape,
                        is_watch: bool, target: str):
        if shape is None:
            # discovery / openapi: identical on every shard (same binary,
            # same scheme) — serve from the first reachable one
            return await self._any_shard(req, target)
        _g, _v, _ns, _res, name, _sub = shape
        headers = self._fwd_headers(req)
        if req.method == "GET" and name is None:
            if is_watch:
                return self._merged_watch(req, target)
            return await self._scatter_list(req, target)
        if req.method == "GET" and name is not None:
            _idx, (s, h, b) = await self._scatter_named(req, target)
            return self._relay(s, h, b)
        if req.method in ("POST", "PUT"):
            try:
                obj = json.loads(req.body) if req.body else None
            except ValueError as e:
                raise errors.BadRequestError(f"malformed JSON body: {e}") from e
            if not isinstance(obj, dict):
                raise errors.BadRequestError("body must be a JSON object")
            # the ONE copy of the wildcard write rule, then the ring; the
            # shard re-resolves the same rule to the same cluster
            wc = resolve_write_cluster(WILDCARD, obj, errors.BadRequestError)
            idx = self.ring.owner_index(wc)
            status, h, body = await self._call(
                idx, req.method, target, req.body, headers)
            return self._relay(status, h, body)
        if req.method == "DELETE" and name is not None:
            # resolve the unique owner with a read scatter FIRST: a
            # wildcard DELETE forwarded to every shard would delete any
            # same-named object that is unique *within* its shard even
            # when it is ambiguous across the fleet
            idx, (s, h, b) = await self._scatter_named(req, target)
            if idx < 0:
                return self._relay(s, h, b)
            status, h2, b2 = await self._call(idx, "DELETE", target, None,
                                              headers)
            return self._relay(status, h2, b2)
        raise errors.BadRequestError(
            f"unsupported method {req.method} for {req.path}")

    async def _any_shard(self, req: Request, target: str) -> Response:
        last: Exception | None = None
        for i in range(len(self.ring)):
            try:
                status, h, body = await self._call(
                    i, req.method, target, req.body or None,
                    self._fwd_headers(req))
                return self._relay(status, h, body)
            except errors.UnavailableError as e:
                last = e
        assert last is not None
        raise last

    async def _scatter_named(self, req: Request, target: str):
        """Resolve a wildcard single-object read across shards: returns
        ``(owner_index, (status, headers, body))`` with owner_index -1
        when there is no unique owner (the triple is then the honest
        error response to relay)."""
        results = await self._scatter("GET", target, self._fwd_headers(req))
        hits = [i for i, (s, _h, _b) in enumerate(results) if 200 <= s < 300]
        if len(hits) == 1:
            return hits[0], results[hits[0]]
        if len(hits) > 1:
            names = [self.ring.shards[i].name for i in hits]
            raise errors.BadRequestError(
                f"object is ambiguous across shards {names}")
        # no shard owns it: relay a shard-local ambiguity (400) over any
        # other error over the plain 404
        for s, h, b in results:
            if s == 400:
                return -1, (s, h, b)
        for s, h, b in results:
            if s != 404:
                return -1, (s, h, b)
        return -1, results[0]

    async def _scatter_list(self, req: Request, target: str) -> Response:
        if req.param("continue") or req.param("limit"):
            return await self._scatter_list_paged(req, target)
        results = await self._scatter("GET", target, self._fwd_headers(req))
        for s, h, b in results:
            if s >= 400:
                # one refusal (authz, unknown resource) refuses the merge
                return self._relay(s, h, b)
        bodies = [b for _s, _h, b in results]
        merged = self._merge_lists(bodies)
        if merged is None:
            merged = self._merge_lists_dict(bodies)
        return Response(body=merged)

    async def _scatter_list_paged(self, req: Request, target: str) -> Response:
        """KEP-365 chunking across the fleet: shards page one at a time,
        in shard order, each pinned at the RV its first-page scatter
        answered — the concatenated pages reproduce exactly what the
        unpaged byte-splice merge serves, because that merge IS the
        shards' sorted bodies in shard order. The client-facing continue
        token packs every shard's pinned RV and per-shard store token
        (:func:`_encode_router_continue`); the page envelope carries the
        vector RV, so the final page anchors watches exactly like the
        one-shot merge. A token minted against a different shard count
        answers typed 410 — re-list, never guess."""
        n = len(self.ring)
        cont = req.param("continue")
        headers = self._fwd_headers(req)
        if not cont:
            # first page: the scatter doubles as the RV-pin snapshot —
            # shard 0's page is served now, every other shard's is
            # discarded but its pinned RV seeds a from-start token
            results = await self._scatter("GET", target, headers)
            for s, h, b in results:
                if s >= 400:
                    return self._relay(s, h, b)
            rvs: list[int] = []
            parsed: list[tuple[bytes, int]] = []
            for _s, _h, body in results:
                i = body.find(_ITEMS_MARKER)
                m = _RV_RE.search(body[:i]) if i >= 0 else None
                if i < 0 or m is None or not body.endswith(b"]}"):
                    # non-standard shape (Table, legacy shard): the
                    # unpaged dict merge is the honest fallback
                    return Response(body=self._merge_lists_dict(
                        [b for _s2, _h2, b in results]))
                parsed.append((body, i))
                rvs.append(int(m.group(1)))
            toks: list[str | None] = []
            for j, (body, i) in enumerate(parsed):
                cm = _CONT_RE.search(body[:i])
                span = body[i + len(_ITEMS_MARKER):-2]
                if j == 0:
                    toks.append(cm.group(1).decode() if cm else None)
                elif cm is None and not span:
                    toks.append(None)  # provably empty at the pin
                else:
                    toks.append(encode_continue(rvs[j], None))
            return self._paged_response(parsed[0][0], rvs, toks)
        decoded = _decode_router_continue(cont, n)
        if decoded is None:
            REGISTRY.counter("list_continue_410_total",
                             "continue tokens answered with 410").inc()
            raise errors.GoneError(
                "continue token does not match this router's shard "
                "topology; re-list")
        rvs, toks = decoded
        idx = next((j for j, t in enumerate(toks) if t is not None), None)
        if idx is None:
            raise errors.GoneError("continue token is exhausted; re-list")
        status, h, body = await self._call(
            idx, "GET", _swap_continue(target, toks[idx]), None, headers)
        if status >= 400:
            # a shard's own 410 (window expired under the pin) relays:
            # the client restarts its chunked list from scratch
            return self._relay(status, h, body)
        i = body.find(_ITEMS_MARKER)
        if i < 0 or _RV_RE.search(body[:i]) is None \
                or not body.endswith(b"]}"):
            raise errors.GoneError(
                f"shard {self.ring.shards[idx].name} answered an "
                "unpageable list body; re-list")
        cm = _CONT_RE.search(body[:i])
        toks[idx] = cm.group(1).decode() if cm else None
        return self._paged_response(body, rvs, toks)

    def _paged_response(self, body: bytes, rvs: list[int],
                        toks: list) -> Response:
        """One shard's page body rewritten into the fleet envelope:
        resourceVersion becomes the vector RV; the shard's own continue
        (never meaningful to a client) is folded into — or replaced by —
        the packed router token."""
        i = body.find(_ITEMS_MARKER)
        head = body[:i + len(_ITEMS_MARKER)]
        tail = body[i + len(_ITEMS_MARKER):]
        router_tok = (_encode_router_continue(rvs, toks)
                      if any(t is not None for t in toks) else None)
        m = _RV_RE.search(head)
        assert m is not None  # caller verified
        head = (head[:m.start(1)] + str(encode_rvmap(rvs)).encode()
                + head[m.end(1):])
        m2 = _CONT_RE.search(head)
        if m2 is not None and router_tok is not None:
            head = (head[:m2.start(1)] + router_tok.encode()
                    + head[m2.end(1):])
        elif router_tok is not None:
            ins = _RV_RE.search(head).end()
            head = (head[:ins] + b', "continue": "' + router_tok.encode()
                    + b'"' + head[ins:])
        return Response(body=head + tail)

    def _merge_lists(self, bodies: list[bytes]) -> bytes | None:
        """Byte-splice shard list bodies into one: per-object bytes are
        exactly what each owning shard serialized (encode-once bytes
        relay untouched); only the envelope's resourceVersion is
        rewritten to the vector RV. None when a body isn't a standard
        list shape (Table renderings take the dict path)."""
        spans: list[bytes] = []
        rvs: list[int] = []
        head0 = None
        m0 = None
        for body in bodies:
            i = body.find(_ITEMS_MARKER)
            if i < 0 or not body.endswith(b"]}"):
                return None
            head = body[:i + len(_ITEMS_MARKER)]
            m = _RV_RE.search(head)
            if m is None:
                return None
            rvs.append(int(m.group(1)))
            if head0 is None:
                head0, m0 = head, m
            span = body[i + len(_ITEMS_MARKER):-2]
            if span:
                spans.append(span)
        assert head0 is not None and m0 is not None
        vec = str(encode_rvmap(rvs)).encode()
        head = head0[:m0.start(1)] + vec + head0[m0.end(1):]
        return head + b", ".join(spans) + b"]}"

    def _merge_lists_dict(self, bodies: list[bytes]) -> bytes:
        docs = [json.loads(b) for b in bodies]
        out = docs[0]
        key = "rows" if out.get("kind") == "Table" else "items"
        merged: list = []
        for d in docs:
            merged.extend(d.get(key) or [])
        out[key] = merged
        rvs = [int((d.get("metadata") or {}).get("resourceVersion", "0"))
               for d in docs]
        out.setdefault("metadata", {})["resourceVersion"] = str(
            encode_rvmap(rvs))
        return json.dumps(out).encode()

    # ------------------------------------------------------ server-global

    async def _version(self, req: Request) -> Response:
        body = dict(self.version_info)
        try:
            results = await self._scatter("GET", "/version",
                                          self._fwd_headers(req))
            rvs = []
            for s, _h, b in results:
                if s >= 400:
                    raise ValueError(f"shard /version answered {s}")
                rv = json.loads(b).get("resourceVersion")
                if rv is None:
                    raise ValueError("shard withheld resourceVersion")
                rvs.append(int(rv))
            body["resourceVersion"] = str(encode_rvmap(rvs))
        except (ValueError, errors.ApiError):
            # version fields stay public; the vector RV is simply omitted
            # when any shard withholds its RV or is unreachable
            pass
        return Response.of_json(body)

    async def _clusters(self, req: Request) -> Response:
        results = await self._scatter("GET", "/clusters",
                                      self._fwd_headers(req))
        for s, h, b in results:
            if s >= 400:
                return self._relay(s, h, b)
        names = sorted({c for _s, _h, b in results
                        for c in json.loads(b).get("clusters", [])})
        return Response.of_json({"clusters": names})

    async def _ring_add(self, req: Request, entry) -> Response:
        """Grow the ring by one shard (``POST /ring {"add": ...}``):
        parse the entry, enumerate the fleet's live clusters, pin every
        cluster whose HRW owner would change to its CURRENT owner (the
        pending-migration overlay), and publish the grown ring. Nothing
        moves yet: the response's ``pending`` list is the migration work
        list — sharding/migrate.py streams each cluster to the new shard
        and then posts ``{"complete": cluster}``, dropping that one pin
        (the atomic per-cluster ownership flip). New clusters created
        after the grow route straight to their HRW owners."""
        if isinstance(entry, dict):
            entry = (f"{entry['name']}={entry['url']}"
                     + "".join("|" + r for r in entry.get("replicas", ())))
        parsed = ShardRing.from_spec(str(entry))
        if len(parsed.shards) != 1:
            raise ValueError(
                f"add takes exactly one shard entry, got {len(parsed.shards)}")
        new = parsed.shards[0]
        # the cluster enumeration must cover every shard or a missed
        # cluster would flip owners without a migration (data loss):
        # _scatter already refuses on any unreachable shard
        results = await self._scatter("GET", "/clusters",
                                      self._fwd_headers(req))
        for s, h, b in results:
            if s >= 400:
                return self._relay(s, h, b)
        clusters = sorted({c for _s, _h, b in results
                           for c in json.loads(b).get("clusters", [])})
        grown_names = [s.name for s in self.ring.shards] + [new.name]
        movers = [
            c for c in clusters
            if c not in self.ring.overrides
            and owner_name(grown_names, c)
            != self.ring.shards[self.ring.owner_index(c)].name]
        ring = self.ring.with_shard_added(new, movers)
        self.set_ring(ring)
        doc = self._ring_doc()
        doc["pending"] = movers
        return Response.of_json(doc)

    # ----------------------------------------------- fleet observability

    def _obs_sources(self) -> list[tuple[str, int, ConnectionPool | None]]:
        """Every scrape/trace source behind this router: each shard's
        primary (pool None = the current primary slot) and its replicas,
        labeled ``s0`` / ``s0/replica0`` style."""
        out: list[tuple[str, int, ConnectionPool | None]] = []
        for i, shard in enumerate(self.ring.shards):
            out.append((shard.name, i, None))
            for j, pool in enumerate(self._rpools[i]):
                out.append((f"{shard.name}/replica{j}", i, pool))
        return out

    async def _fan_fetch(self, target: str, headers: dict[str, str]
                         ) -> list[tuple[str, bytes | None, str]]:
        """GET ``target`` from every source in parallel; returns
        ``(label, body-or-None, error)`` per source — failures are
        reported, never silently dropped."""
        sources = self._obs_sources()

        async def one(label: str, idx: int, pool):
            try:
                status, _h, body = await self._call(
                    idx, "GET", target, None, headers, pool=pool,
                    who=label)
            except (errors.ApiError, ConnectionError, OSError) as e:
                return (label, None, f"{type(e).__name__}: {e}")
            if status >= 400:
                return (label, None, f"HTTP {status}")
            return (label, body, "")

        return list(await asyncio.gather(
            *(one(label, idx, pool) for label, idx, pool in sources)))

    async def _metrics_fleet(self, req: Request) -> Response:
        """``GET /metrics?fleet=1``: scatter every shard's and replica's
        ``/metrics``, re-emit as one exposition with a ``shard=<label>``
        label on every sample (the router's own metrics ride as
        ``shard="router"``). A partial scatter is annotated with a
        comment per missing source and counted
        (``router_fleet_scrape_failed_total``) — the gauntlet scrapes
        one endpoint and still learns the truth."""
        results = await self._fan_fetch("/metrics", self._fwd_headers(req))
        parts: list[tuple[str, str]] = [("router", REGISTRY.expose())]
        notes: list[str] = []
        for label, body, err in results:
            if body is None:
                notes.append(f"# fleet: source {label} unreachable: {err}")
                REGISTRY.counter(
                    "router_fleet_scrape_failed_total",
                    "fleet metrics federation scrapes that could not "
                    "reach a shard or replica").inc()
            else:
                parts.append((label, body.decode("utf-8", "replace")))
        merged = _merge_expositions(parts)
        text = ("\n".join(notes) + "\n" if notes else "") + merged
        return Response(body=text.encode("utf-8"),
                        content_type="text/plain; version=0.0.4")

    async def _trace_scatter(self, req: Request) -> Response:
        """Assemble cross-process traces: scatter ``/debug/trace`` to
        every shard and replica, merge their span buffers with the
        router's own. ``?id=`` unions one trace's spans;
        ``?slowest=N`` re-ranks the union of everyone's slowest."""
        tracer = obs.TRACER
        tid = req.param("id")
        query = (f"/debug/trace?id={quote(tid)}" if tid
                 else f"/debug/trace?slowest={quote(req.param('slowest'))}")
        results = await self._fan_fetch(query, self._fwd_headers(req))
        partial = [f"{label}: {err}" for label, body, err in results
                   if body is None]
        docs = []
        for _label, body, _err in results:
            if body is None:
                continue
            try:
                docs.append(json.loads(body))
            except ValueError:
                continue
        if tid:
            spans = {(s["trace"], s["span"]): s for s in tracer.get(tid)}
            for d in docs:
                for s in d.get("spans", []):
                    spans.setdefault((s["trace"], s["span"]), s)
            out = sorted(spans.values(), key=lambda s: s["t0"])
            return Response.of_json({
                "id": tid, "proc": tracer.proc, "spans": out,
                "partial": partial})
        try:
            n = max(1, min(int(req.param("slowest") or "3"), 32))
        except ValueError:
            n = 3
        by_trace: dict[str, dict] = {}
        for t in tracer.slowest(n):
            by_trace[t["id"]] = {(s["trace"], s["span"]): s
                                 for s in t["spans"]}
        for d in docs:
            for t in d.get("traces", []):
                ent = by_trace.setdefault(t["id"], {})
                for s in t.get("spans", []):
                    ent.setdefault((s["trace"], s["span"]), s)
        ranked = []
        for t_id, spans in by_trace.items():
            vals = list(spans.values())
            t0 = min(s["t0"] for s in vals)
            t1 = max(s["t0"] + s["dur"] for s in vals)
            ranked.append({"id": t_id, "dur": round(t1 - t0, 6),
                           "spans": sorted(vals, key=lambda s: s["t0"])})
        ranked.sort(key=lambda t: -t["dur"])
        return Response.of_json({
            "proc": tracer.proc, "traces": ranked[:n], "partial": partial})

    # -------------------------------------------------------------- watch

    def _tap_watch(self, idx: int, target: str, req: Request,
                   pool: ConnectionPool | None = None) -> _TapWatch:
        if pool is None:
            pool = self._pools[idx]
        parts = urlsplit(pool.base_url)
        host = parts.hostname or "127.0.0.1"
        port = parts.port or (443 if parts.scheme == "https" else 80)
        auth = req.headers.get("authorization", "")
        token = auth[7:] if auth.lower().startswith("bearer ") else pool.token
        return _TapWatch(host, port, target, "", token=token,
                         ssl_context=pool.ssl_context)

    def _stream_proxy(self, idx: int, target: str, req: Request,
                      pool: ConnectionPool | None = None) -> StreamResponse:
        """Single-cluster watch: a ZERO-PARSE stream relay to the owning
        shard — upstream length-delimited chunks forward verbatim (size
        line + payload bytes untouched: no utf-8 decode, no line split,
        no per-event json parse — the ``_TapWatch`` parse survives only
        on merged wildcard watches, which genuinely need per-shard
        positions). Resume RVs stay shard-local and honest (the ring
        maps the cluster back to the same shard). ``pool`` targets a
        read replica for fresh watches."""
        shard = self.ring.shards[idx]
        use = pool if pool is not None else self._pools[idx]
        parts = urlsplit(use.base_url)
        host = parts.hostname or "127.0.0.1"
        port = parts.port or (443 if parts.scheme == "https" else 80)
        auth = req.headers.get("authorization", "")
        token = auth[7:] if auth.lower().startswith("bearer ") else use.token
        ssl_ctx = use.ssl_context
        tp = self._fwd_headers(req).get(obs.TRACEPARENT)

        async def produce(stream: StreamResponse) -> None:
            reader = writer = None
            try:
                try:
                    reader, writer = await asyncio.open_connection(
                        host, port, ssl=ssl_ctx,
                        server_hostname=host if ssl_ctx else None)
                except (ConnectionError, OSError) as e:
                    self._unavailable.inc()
                    await stream.send_json({
                        "type": "ERROR",
                        "object": _status_body(
                            503, "ServiceUnavailable",
                            f"shard {shard.name} unreachable: {e}")})
                    return
                lines = [f"GET {target} HTTP/1.1", f"Host: {host}"]
                if token:
                    lines.append(f"Authorization: Bearer {token}")
                if tp:
                    lines.append(f"{obs.TRACEPARENT}: {tp}")
                lines.append("Connection: close")
                writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
                await writer.drain()
                head = await reader.readuntil(b"\r\n\r\n")
                code = int(head.split(b"\r\n", 1)[0].split(b" ")[1])
                if code >= 400:
                    # the shard refused the watch: relay its Status
                    # in-stream like every other relay refusal
                    body = await reader.read(64 * 1024)
                    raw = body[body.find(b"{"):body.rfind(b"}") + 1]
                    try:
                        status = json.loads(raw)
                    except ValueError:
                        status = _status_body(
                            code, "",
                            f"shard {shard.name} refused the watch "
                            f"({code})")
                    await stream.send_json({"type": "ERROR",
                                            "object": status})
                    return
                while True:
                    size_line = await reader.readline()
                    if not size_line:
                        return  # upstream died: clean end, client resumes
                    size = int(size_line.strip() or b"0", 16)
                    if size == 0:
                        return  # upstream terminal chunk: clean end
                    payload = await reader.readexactly(size + 2)
                    self._raw_chunks.inc()
                    await stream.relay_chunk(size_line, payload)
            except (ConnectionError, asyncio.IncompleteReadError, OSError,
                    ValueError):
                return  # stream garbled or torn down mid-relay
            finally:
                if writer is not None:
                    writer.close()

        return StreamResponse(produce)

    def _watch_target(self, req: Request, target: str,
                      since_rv: int | None) -> str:
        """Rebuild a per-shard watch target: the shard's own resume RV
        replaces the client's, and shard-side bookmarks are always on —
        they feed the vector-RV position map even when the client asked
        for none."""
        path, _sep, _q = target.partition("?")
        params = {k: v[0] for k, v in req.query.items()}
        if since_rv is not None:
            params["resourceVersion"] = str(since_rv)
        else:
            params.pop("resourceVersion", None)
        params["allowWatchBookmarks"] = "true"
        return path + "?" + urlencode(params, quote_via=quote)

    def _merged_watch(self, req: Request, target: str) -> StreamResponse:
        n = len(self.ring)
        since = req.param("resourceVersion")
        want_bookmarks = req.param("allowWatchBookmarks") in ("true", "1")

        async def produce(stream: StreamResponse) -> None:
            rvs: list[int] | None = None
            if since:
                try:
                    value = int(since)
                except ValueError:
                    await stream.send_json({
                        "type": "ERROR",
                        "object": _status_body(
                            400, "BadRequest",
                            f"malformed resourceVersion {since!r}")})
                    return
                rvs = decode_rvmap(value, n)
                if rvs is None:
                    # a scalar (or foreign-ring) RV carries no per-shard
                    # positions — resuming from it would either replay or
                    # skip arbitrarily on every shard. Honest answer: 410,
                    # client re-lists and gets a vector RV.
                    await stream.send_json({
                        "type": "ERROR",
                        "object": _status_body(
                            410, "Expired",
                            f"resourceVersion {since} is not a vector RV "
                            f"for this {n}-shard ring; re-list")})
                    return
                self._resumes.inc()
            pos = list(rvs) if rvs else [0] * n
            known = [rvs is not None] * n
            q: asyncio.Queue = asyncio.Queue()
            watches: list[_TapWatch] = []
            pumps: list[asyncio.Task] = []
            try:
                for i in range(n):
                    t = self._watch_target(
                        req, target, rvs[i] if rvs else None)
                    watches.append(self._tap_watch(i, t, req))

                async def pump(i: int, w: _TapWatch) -> None:
                    while True:
                        item = await w.next()
                        await q.put((i, item))
                        if item is None:
                            return

                pumps = [asyncio.ensure_future(pump(i, w))
                         for i, w in enumerate(watches)]
                while True:
                    try:
                        i, item = await asyncio.wait_for(
                            q.get(), timeout=self.bookmark_every)
                    except asyncio.TimeoutError:
                        # idle: a vector bookmark, but only once every
                        # shard has reported an honest position — a
                        # guessed 0 would rewind a resuming client into
                        # a replay (or a 410) it never asked for
                        if want_bookmarks and all(known):
                            await stream.send_json({
                                "type": "BOOKMARK",
                                "object": {"kind": "Bookmark", "metadata": {
                                    "resourceVersion": str(encode_rvmap(pos))}},
                            })
                        continue
                    if item is None:
                        # shard stream died (process death, connection
                        # loss): merged coverage is gone — terminal 410 so
                        # the client re-lists and resumes from a fresh
                        # vector (PR 2 discipline: fail loudly in-stream,
                        # never silently serve a partial fleet)
                        err = watches[i].error
                        msg = f"shard {self.ring.shards[i].name} watch ended"
                        if err is not None:
                            msg += f": {getattr(err, 'message', err)}"
                        await stream.send_json({
                            "type": "ERROR",
                            "object": _status_body(410, "Expired",
                                                   msg + "; re-list required")})
                        return
                    raw, msg = item
                    mtype = msg.get("type")
                    meta = (msg.get("object") or {}).get("metadata") or {}
                    try:
                        rv = int(meta.get("resourceVersion", "0"))
                    except (TypeError, ValueError):
                        rv = 0
                    if mtype == "BOOKMARK":
                        # shard-local progress marker: absorbed into the
                        # position map, never relayed (its scalar RV
                        # would poison the client's resume)
                        if rv:
                            pos[i] = max(pos[i], rv)
                            known[i] = True
                        continue
                    if mtype == "ERROR":
                        # the shard refused or expired this stream:
                        # relay its typed Status verbatim and end — the
                        # merge cannot continue with partial coverage
                        await stream.send_raw_many([raw])
                        return
                    if rv:
                        pos[i] = max(pos[i], rv)
                        known[i] = True
                    await stream.send_raw_many([raw])
            finally:
                for p in pumps:
                    p.cancel()
                for w in watches:
                    w.close()

        return StreamResponse(produce)
