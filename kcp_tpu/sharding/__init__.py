"""kcp_tpu.sharding — the horizontally-sharded control plane.

One process scales *within* itself (pipelined ticks, indexed stores,
encode-once serving); the BASELINE north star — 1M reconciles/sec across
10k logical clusters — needs N of them. This package partitions logical
clusters across shard servers with a consistent-hash ring
(:mod:`.ring`, rendezvous/HRW) and fronts the fleet with a router
(:mod:`.router`) that speaks the unchanged REST surface: single-cluster
requests proxy byte-verbatim to the owning shard, wildcard lists/watches
scatter-gather and merge under vector-RV bookkeeping (:mod:`.rvmap`).

Run it: ``kcp start --role shard`` per shard (a plain server), then
``kcp start --role router --shards s0=http://h0:6443,s1=http://h1:6443``.
"""

from .ring import Shard, ShardRing, owner_name
from .router import RouterHandler
from .rvmap import decode_rvmap, encode_rvmap

__all__ = ["Shard", "ShardRing", "RouterHandler", "owner_name",
           "decode_rvmap", "encode_rvmap"]
