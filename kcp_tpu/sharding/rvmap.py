"""Vector resourceVersion: the router's honest RV across N shards.

Each shard allocates its own monotonically increasing store RV, so a
single scalar cannot describe a merged wildcard list/watch position —
"resume from 1742" is meaningless when three independent counters are
involved. The router therefore reports a *vector* RV: the per-shard RV
list, packed into one arbitrary-precision integer so it rides the
existing wire surface unchanged (``metadata.resourceVersion`` strings,
``?resourceVersion=`` watch resumes, ``int()`` round trips in RestClient
and the informer all keep working — Python ints are unbounded).

Encoding: ``MAGIC(2B) | shard-count(1B) | LEB128 varint per shard RV``,
big-endian int of those bytes. The magic keeps any plausible scalar
store RV (which would need to exceed 2^40 *and* collide with the magic
prefix AND parse to the exact byte length) from masquerading as a
vector; decoding is strict — wrong magic, wrong shard count, or trailing
bytes all return ``None``, and the router answers such resumes with an
honest 410 Gone (re-list) instead of guessing.
"""

from __future__ import annotations

MAGIC = b"\xc5\x52"  # arbitrary, non-zero leading byte (survives int round trip)
MAX_SHARDS = 255


def _varint(n: int, out: bytearray) -> None:
    if n < 0:
        raise ValueError(f"negative rv {n}")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def encode_rvmap(rvs: list[int]) -> int:
    """Pack per-shard RVs (ring order) into one opaque integer."""
    if not rvs or len(rvs) > MAX_SHARDS:
        raise ValueError(f"rv vector of {len(rvs)} shards (1..{MAX_SHARDS})")
    out = bytearray(MAGIC)
    out.append(len(rvs))
    for rv in rvs:
        _varint(int(rv), out)
    return int.from_bytes(bytes(out), "big")


def decode_rvmap(value: int, n_shards: int) -> list[int] | None:
    """Unpack a vector RV for an ``n_shards`` ring; ``None`` when the
    value is not a vector for exactly that ring size (a plain scalar RV,
    a vector minted by a differently-sized ring, garbage)."""
    if value <= 0:
        return None
    raw = value.to_bytes((value.bit_length() + 7) // 8, "big")
    if len(raw) < 4 or raw[:2] != MAGIC or raw[2] != n_shards:
        return None
    rvs: list[int] = []
    i = 3
    for _ in range(n_shards):
        rv = 0
        shift = 0
        while True:
            if i >= len(raw) or shift > 63:
                return None
            b = raw[i]
            i += 1
            rv |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        rvs.append(rv)
    if i != len(raw):  # trailing bytes: not our encoding
        return None
    return rvs
