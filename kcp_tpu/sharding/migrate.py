"""Live per-cluster migration: move one logical cluster between shards
with zero lost acked writes and zero lost watch events.

The engine is a synchronous client-side driver (it runs wherever the
operator — or ``RouterFleet.scale_out`` — runs; no shard hosts it) that
composes surfaces the fleet already has:

1. **fence** — ``POST /migration/fence`` on the source pins the cluster
   read-only at a *cutover RV*: every write the source ever acked for
   the cluster has rv <= cutover (the store's group-commit barrier
   flushes in-flight windows first). Fenced writes refuse 503; clients
   retry and land on the new owner once the ring flips.
2. **stream** — ``GET /replication/wal?cluster=X&role=migration`` on the
   source serves the cluster's post-fence snapshot through the PR 9
   replication hub (SNAP records, then BARRIER — the fence makes the
   filtered snapshot the cluster's final state), and the records POST to
   the target's ``/migration/ingest`` as WAL-shaped ndjson — the same
   shape ``scripts/walreplay.py --cluster --emit-ndjson`` extracts
   offline, which is what makes walreplay the transport oracle in tests.
3. **finish** — ``POST /migration/finish`` on the target jumps its RV
   counter past the source's cutover and records the cluster's resume
   floor: a watch resume carrying a source-minted RV answers a typed
   410 (re-list), never a silent partial resume against an unrelated
   RV history.
4. **flip** — ``POST /ring {"complete": cluster}`` on the router drops
   the cluster's pending-migration pin: ownership flips atomically for
   this one cluster, the epoch bumps, and the ring (with overrides)
   fans out to every shard. Smart clients re-fetch on their next 410.
5. **purge** — ``POST /migration/purge`` on the source evicts the
   cluster's watch streams through the backpressure-eviction path
   (buffered events drain FIRST, then a terminal typed 410 → relist at
   the new owner) and drops the objects with no watch events — a move
   is not a delete.

Any failure before the flip rolls the fence back (``unfence``) so an
aborted migration never strands the cluster unwritable; the whole
sequence is idempotent and re-runnable. ``migrate.cutover`` is the
KCP_FAULTS drill point between finish and flip — the worst possible
instant to die (target loaded, ring not flipped) — proving the
rollback leaves the fleet serving from the source.

Metered: ``migration_seconds`` (per-cluster wall time),
``migration_records_total`` (applied on the target, store-side),
``migration_fenced_writes_total`` (refusals during the fence window,
store-side).
"""

from __future__ import annotations

import http.client
import json
import logging
import time
from urllib.parse import quote, urlsplit

from ..faults import maybe_fail
from ..utils.trace import REGISTRY
from .ring import owner_name

log = logging.getLogger(__name__)

_SECONDS = REGISTRY.histogram(
    "migration_seconds",
    "end-to-end wall time migrating one logical cluster between shards "
    "(fence -> stream -> finish -> ring flip -> purge)")


class MigrationError(RuntimeError):
    """A migration step refused or the transport broke; the fence has
    been rolled back (ownership never flips on a failed migration)."""


def _connect(base_url: str, timeout: float):
    p = urlsplit(base_url)
    cls = (http.client.HTTPSConnection if p.scheme == "https"
           else http.client.HTTPConnection)
    return cls(p.hostname, p.port, timeout=timeout)


def _req(base_url: str, method: str, target: str, body=None,
         token: str = "", timeout: float = 30.0) -> dict:
    """One JSON round trip; raises MigrationError on any >=400 answer
    (every step must succeed explicitly — a migration has no partial
    credit)."""
    headers: dict[str, str] = {}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    payload = None
    if body is not None:
        payload = (body if isinstance(body, (bytes, bytearray))
                   else json.dumps(body).encode())
        headers["Content-Type"] = "application/json"
    c = _connect(base_url, timeout)
    try:
        c.request(method, target, payload, headers)
        r = c.getresponse()
        data = r.read()
        if r.status >= 400:
            raise MigrationError(
                f"{method} {base_url}{target} answered {r.status}: "
                f"{data[:300].decode('utf-8', 'replace')}")
        return json.loads(data) if data else {}
    except (ConnectionError, OSError, TimeoutError,
            http.client.HTTPException) as e:
        raise MigrationError(
            f"{method} {base_url}{target} unreachable: {e}") from e
    finally:
        c.close()


def fetch_cluster_records(source_url: str, cluster: str, token: str = "",
                          timeout: float = 120.0
                          ) -> tuple[list[dict], int]:
    """Stream one cluster's post-fence snapshot off the source's
    filtered replication feed; returns (WAL-shaped put records, the
    BARRIER rv). The BARRIER bounds every RV the source ever minted for
    the cluster — it becomes the target's ``finish`` watermark."""
    headers: dict[str, str] = {"Accept": "application/x-ndjson"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    target = (f"/replication/wal?cluster={quote(cluster, safe='')}"
              f"&role=migration&sinceRV=0&epoch=0")
    recs: list[dict] = []
    barrier_rv = None
    c = _connect(source_url, timeout)
    try:
        c.request("GET", target, None, headers)
        r = c.getresponse()
        if r.status >= 400:
            raise MigrationError(
                f"migration feed {source_url}{target} answered {r.status}")
        while True:
            line = r.readline()
            if not line:
                break
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            t = msg.get("type")
            if t == "HEADER":
                continue
            if t == "SNAP":
                recs.append({"op": "put", "key": msg["key"],
                             "obj": msg["obj"]})
            elif t == "BARRIER":
                barrier_rv = int(msg["rv"])
                break
            elif t == "ERROR":
                raise MigrationError(
                    f"migration feed refused: {msg.get('object')}")
    except (ConnectionError, OSError, TimeoutError, ValueError,
            http.client.HTTPException) as e:
        raise MigrationError(
            f"migration feed {source_url} broke mid-stream: {e}") from e
    finally:
        c.close()
    if barrier_rv is None:
        raise MigrationError(
            "migration feed ended before its BARRIER — transport torn; "
            "nothing was applied, the fence is being rolled back")
    return recs, barrier_rv


def ingest_records(target_url: str, recs: list[dict], token: str = "",
                   batch: int = 256, timeout: float = 60.0) -> int:
    """POST WAL-shaped records to the target's ``/migration/ingest`` in
    ndjson batches; returns records applied. Also the offline path:
    ``walreplay.py --cluster X --emit-ndjson`` output pipes here."""
    applied = 0
    for i in range(0, len(recs), batch):
        payload = b"".join(
            json.dumps(rec, separators=(",", ":")).encode() + b"\n"
            for rec in recs[i:i + batch])
        out = _req(target_url, "POST", "/migration/ingest", payload,
                   token=token, timeout=timeout)
        applied += int(out.get("applied", 0))
    return applied


def migrate_cluster(router_url: str, cluster: str, *, token: str = "",
                    batch: int = 256, timeout: float = 120.0) -> dict:
    """Move one pinned cluster to its HRW owner under the grown ring.

    The cluster must already carry a pending-migration pin (the router's
    ``{"add": ...}`` installs them); source and target are derived from
    the ring document, so the caller names only the cluster."""
    t0 = time.monotonic()
    doc = _req(router_url, "GET", "/ring", token=token, timeout=timeout)
    shards = {s["name"]: s["url"] for s in doc.get("shards", ())}
    overrides = doc.get("overrides") or {}
    src_name = overrides.get(cluster)
    if src_name is None:
        raise MigrationError(
            f"cluster {cluster!r} has no pending migration "
            f"(overrides: {sorted(overrides)})")
    dst_name = owner_name(list(shards), cluster)
    if dst_name == src_name:
        # the pin points at the HRW owner already (a completed retry, or
        # the grow didn't move this cluster after all): just flip
        _req(router_url, "POST", "/ring", {"complete": cluster},
             token=token, timeout=timeout)
        return {"cluster": cluster, "source": src_name,
                "target": dst_name, "records": 0, "noop": True}
    src_url, dst_url = shards[src_name], shards[dst_name]
    cutover = int(_req(src_url, "POST", "/migration/fence",
                       {"cluster": cluster}, token=token,
                       timeout=timeout)["cutover_rv"])
    try:
        recs, barrier = fetch_cluster_records(src_url, cluster,
                                              token=token, timeout=timeout)
        applied = ingest_records(dst_url, recs, token=token, batch=batch,
                                 timeout=timeout)
        _req(dst_url, "POST", "/migration/finish",
             {"cluster": cluster, "source_rv": max(cutover, barrier)},
             token=token, timeout=timeout)
        # the cutover drill: dying HERE — target loaded, ring not yet
        # flipped — is the worst instant; the except below proves the
        # fleet keeps serving from the source (fence rolled back)
        delay = maybe_fail("migrate.cutover")
        if delay:
            time.sleep(delay)
        _req(router_url, "POST", "/ring", {"complete": cluster},
             token=token, timeout=timeout)
    except BaseException:
        try:
            _req(src_url, "POST", "/migration/unfence",
                 {"cluster": cluster}, token=token, timeout=timeout)
        except MigrationError as e:
            log.warning("fence rollback for %s failed (%s); the cluster "
                        "stays fenced until a retry or manual unfence",
                        cluster, e)
        raise
    # past the flip the migration is irrevocable: purge must not undo it
    _req(src_url, "POST", "/migration/purge", {"cluster": cluster},
         token=token, timeout=timeout)
    dur = time.monotonic() - t0
    _SECONDS.observe(dur)
    log.info("cluster %s migrated %s -> %s: %d records, cutover rv %d, "
             "%.3fs", cluster, src_name, dst_name, applied, cutover, dur)
    return {"cluster": cluster, "source": src_name, "target": dst_name,
            "records": applied, "cutover_rv": cutover,
            "seconds": round(dur, 3)}


def scale_out(router_url: str, entry: str, *, token: str = "",
              batch: int = 256, timeout: float = 120.0) -> dict:
    """Grow a live fleet by one shard: publish the grown ring (every
    moving cluster pinned to its current owner), then migrate the
    pinned clusters one at a time — each flips atomically when its own
    stream lands. ``entry`` is one KCP_SHARDS-shaped shard entry
    (``name=url[|replica-url...]``)."""
    doc = _req(router_url, "POST", "/ring", {"add": entry}, token=token,
               timeout=timeout)
    pending = list(doc.get("pending", ()))
    log.info("ring grown to %d shards (epoch %d): migrating %d clusters",
             len(doc.get("shards", ())), doc.get("epoch", 0), len(pending))
    migrated = [migrate_cluster(router_url, c, token=token, batch=batch,
                                timeout=timeout) for c in pending]
    return {"added": entry, "pending": pending, "migrated": migrated,
            "records": sum(m["records"] for m in migrated)}
