"""LogicalStore: the multi-tenant keyspace + watch hub.

This is the storage layer of the framework — the analog of the reference's
embedded etcd plus the forked apiserver's logical-cluster storage prefixing
(reference: pkg/etcd/etcd.go; docs/investigations/logical-clusters.md:66-74,
key scheme ``/<resource>/<cluster>/<namespace>/<name>``). It is deliberately
also the test fake: the same object backs unit tests, the in-process API
server, and the fake physical clusters.

Semantics implemented (inferred from the reference's call sites, since the
kcp-dev/kubernetes fork is not vendored there):

- logical-cluster prefix keys; ``*`` (WILDCARD) lists/watches across all
  tenants (logical-clusters.md:70-74)
- a single monotonically increasing resourceVersion per store (etcd
  revision analog); lists carry the store RV, watches can resume from an RV
- optimistic concurrency: update with a stale metadata.resourceVersion
  raises ConflictError
- generation bumps on spec (non-status) changes only; status subresource
  updates never bump generation
- finalizers: delete sets deletionTimestamp first; object is removed when
  the finalizer list is empty
- label-selector filtered list/watch
- optional durability via an append-only JSON-lines WAL with snapshot
  compaction (restart resumes from durable storage, matching the
  reference's restart-resumes-from-etcd model, server.go:80-97)

Read path (KCP_STORE_INDEX=1, the default):

- secondary ``resource -> cluster -> namespace`` buckets are maintained
  on every mutation (and rebuilt on WAL/snapshot restore), so ``list``
  touches only candidate keys instead of every object in the process;
- copy-on-write objects: stored snapshots are never mutated in place
  (every write replaces the whole dict), so ``list`` results and watch
  ``Event`` objects share references with the store and the deep copy
  is deferred to the mutation boundary — callers treat listed objects
  and event payloads as frozen and re-``get`` (or deepcopy) before
  editing, exactly like client-go informer caches;
- watch fan-out is batched: ``_emit`` coalesces events into
  micro-batches and matches each batch against all registered watch
  selectors in one vectorized pass (ops/labelmatch host twins over
  interned label ids — exact, no hash collisions), preserving the
  old-match/new-match ADDED/MODIFIED/DELETED rewrite semantics of
  :meth:`Watch._transform`. Batches flush at the asyncio loop boundary
  (``call_soon``), on a size threshold, and lazily whenever a consumer
  touches a watch, so delivery semantics are unchanged.

``KCP_STORE_INDEX=0`` (or ``indexed=False``) keeps the pre-index scan +
per-event deepcopy path for A/B measurement (``bench.py --store``).

Encode-once serving (KCP_ENCODE_CACHE=1, the default, indexed stores):

- the CoW contract above makes serialized bytes a *pure function of the
  snapshot object*: a per-record byte cache (:meth:`encode_obj`) is
  populated lazily on first encode and needs no invalidation protocol —
  a mutation replaces the snapshot, so the identity-keyed entry simply
  stops matching (replaced/deleted snapshots are evicted for memory
  only, not correctness);
- watch events carry their encoded ``{"type", "object"}`` wire line on
  the :class:`Event` itself (:meth:`encode_event`), so a burst fanned
  out to 64 relays is encoded once, not 64 times — rewritten
  (label-transition) events are shared across matched watches for the
  same reason;
- ``KCP_ENCODE_CACHE=0`` keeps the per-call ``json.dumps`` serving path
  for A/B (``bench.py --encode``), and the ``encode.cache`` KCP_FAULTS
  point force-drops cached entries to exercise the re-encode fallback.

Watcher scale (PR 11):

- the retained history is the **watch-cache window** (``KCP_WATCH_WINDOW``
  events) with a bisect-able shared index: a resume is one binary search
  plus a suffix replay of shared Event instances (so the encode-once wire
  bytes are shared across every resumer of a reconnect storm);
- per-watcher queues are **bounded** (``KCP_WATCH_QUEUE``): a consumer
  that stops draining is EVICTED — ``Watch.evicted`` set, stream closed —
  and the HTTP relay turns that into a terminal in-stream typed 410 so
  informers relist-NOW and resume (the ``watch.evict`` fault point drills
  the path);
- the fan-out keeps a per-resource watch index with cached scope/selector
  arrays (rebuilt only when the watch set changes), so a flush is
  O(events + deliveries), not O(live watchers).

Write path: group commit (KCP_GROUP_COMMIT=1, the default):

- concurrent mutations apply to the in-memory state one at a time as
  always (RV allocation, conflict checks, event emission unchanged),
  but their WAL records coalesce into a bounded **commit window**
  (KCP_COMMIT_WINDOW_MAX rows / KCP_COMMIT_WINDOW_US linger; 0 = close
  at the next loop pass) whose flush is ONE buffered WAL append + ONE
  KCP_WAL_SYNC-policy flush/fsync (both backends — the native engine's
  ws_batch_begin/commit), ONE replication batch, and ONE watch fan-out
  flush;
- writers needing a durability barrier await :meth:`commit_durable`,
  which resolves with the window's high RV after the sync (the serving
  layer parks every writer's semi-sync standby wait there — one ack
  per window) — with an idle fast path that flushes synchronously when
  nothing else can join, so a lone writer pays the serial path's
  latency;
- a window whose sync fails fails every parked writer with a typed 503
  and commits NONE of its records (``store.commit_window`` faults
  drill the split/failure/abort paths); sync-context callers (no
  running loop) keep the serial append — durable on return;
- ``KCP_GROUP_COMMIT=0`` keeps the serial path as the A/B reference:
  state, event streams and WAL bytes are identical either way
  (tests/test_group_commit.py differential fuzz; bench.py --writes).

Thread-model: single-threaded synchronous core intended to be called from
one asyncio event loop; watches buffer into deques and optionally notify an
asyncio.Event so async consumers can await new events.
"""

from __future__ import annotations

import asyncio
import base64
import copy
import json
import logging
import os
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Mapping

import numpy as np

from .. import obs
from ..analysis import sanitize as _sanitize
from ..faults import maybe_fail, should_drop
from ..utils.errors import (
    AlreadyExistsError,
    ConflictError,
    GoneError,
    InvalidError,
    NotFoundError,
    UnavailableError,
)
from ..utils.trace import REGISTRY, SIZE_BUCKETS
from .selectors import LabelSelector, everything

log = logging.getLogger(__name__)

WILDCARD = "*"

# KEP-3157-style watch-list: the sync bookmark that ends the initial
# ADDED stream carries this annotation set to "true"
BOOKMARK = "BOOKMARK"
INITIAL_EVENTS_END = "kcp.io/initial-events-end"


def encode_continue(rv: int, last_key: tuple | list | None) -> str:
    """Opaque KEP-365-style continue token: urlsafe base64 of
    ``{"rv": N, "k": [cluster, namespace, name] | null}``. ``k=null``
    means "from the start, pinned at rv" (the router synthesizes these
    for shards whose first page it discards)."""
    payload = {"rv": int(rv), "k": list(last_key) if last_key else None}
    raw = json.dumps(payload, separators=(",", ":")).encode()
    return base64.urlsafe_b64encode(raw).decode()


def decode_continue(token: str) -> tuple[int, tuple | None]:
    """Inverse of :func:`encode_continue`; raises ValueError on any
    malformed token (callers answer typed 410 — the client re-lists)."""
    try:
        payload = json.loads(base64.urlsafe_b64decode(token.encode()))
        rv = int(payload["rv"])
        k = payload.get("k")
        if k is not None:
            k = tuple(k)
            if len(k) != 3 or not all(isinstance(p, str) for p in k):
                raise ValueError(f"bad continue key {k!r}")
        return rv, k
    except (ValueError, KeyError, TypeError) as e:
        raise ValueError(f"malformed continue token: {e}") from None


def _env_indexed() -> bool:
    return os.environ.get("KCP_STORE_INDEX", "1").lower() not in ("0", "false", "off")


def _env_encode_cache() -> bool:
    return os.environ.get("KCP_ENCODE_CACHE", "1").lower() not in ("0", "false", "off")


def _env_watch_window() -> int:
    """Retained watch-cache window (events): how far back a
    ``watch(since_rv=...)`` resume can reach before answering 410."""
    return int(os.environ.get("KCP_WATCH_WINDOW", "200000"))


def _env_watch_queue() -> int:
    """Per-watcher event-queue bound (0 = unbounded, the legacy
    behavior). A watcher whose consumer stops draining past the bound is
    EVICTED — closed with ``Watch.evicted`` set, which the HTTP relay
    turns into a terminal in-stream typed 410 (informers relist-NOW and
    resume) — instead of buffering the window into unbounded memory."""
    return int(os.environ.get("KCP_WATCH_QUEUE", "65536"))


def _env_group_commit() -> bool:
    """Group commit (KCP_GROUP_COMMIT, default on): concurrent mutations
    coalesce into one commit window — the window's WAL records append as
    ONE buffered write + ONE sync, ship to replication as ONE batch, and
    fan out to watchers in ONE flush. ``=0`` keeps the serial
    append-per-record path (the A/B reference; byte-identical WAL/state
    either way)."""
    return os.environ.get("KCP_GROUP_COMMIT", "1").lower() not in (
        "0", "false", "off")


def _env_commit_window_max() -> int:
    """Commit-window row bound (KCP_COMMIT_WINDOW_MAX): a window holding
    this many records flushes immediately instead of waiting out the
    linger — bounds both ack latency and the blast radius of one failed
    sync."""
    return max(1, int(os.environ.get("KCP_COMMIT_WINDOW_MAX", "256")))


def _env_commit_window_us() -> float:
    """Commit-window linger (KCP_COMMIT_WINDOW_US, microseconds). ``0``
    (the default) closes the window at the next event-loop pass — every
    mutation already runnable this pass joins it, so the idle case pays
    one loop iteration, not a timer. ``>0`` holds the window open that
    long to accumulate more writers per sync at the cost of added write
    latency."""
    return max(0.0, float(os.environ.get("KCP_COMMIT_WINDOW_US", "0")))


def _env_wal_sync() -> str:
    """WAL sync policy (KCP_WAL_SYNC): what one commit (window or serial
    record) costs in durability terms.

    - ``flush`` (default): python/user-space buffers flushed to the OS
      per commit; the native engine keeps its legacy ``sync_every``
      batched fsync. Survives process death, NOT power loss.
    - ``fsync``: fsync per commit — full durability; group commit is
      what makes this affordable (one fsync per window, not per write).
    - ``off``: no explicit flush at all; the OS (and python's buffer)
      decide. Maximum throughput, weakest guarantee.
    """
    mode = os.environ.get("KCP_WAL_SYNC", "flush").lower()
    if mode not in ("flush", "fsync", "off"):
        raise InvalidError(
            f"unknown KCP_WAL_SYNC {mode!r} (flush|fsync|off)")
    return mode


class _CommitWindow:
    """One open group-commit window: the records awaiting their shared
    WAL append + sync, the future every writer of the window parks on
    (resolved with the window's high RV after a successful sync; the
    typed sync error otherwise), and the scheduled flush callback."""

    __slots__ = ("recs", "fut", "high_rv", "handle", "flushed")

    def __init__(self, fut: "asyncio.Future"):
        self.recs: list[dict] = []
        self.fut = fut
        self.high_rv = 0
        self.handle = None
        self.flushed = False

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

Key = tuple[str, str, str, str]  # (resource, cluster, namespace, name)


@dataclass(frozen=True)
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    resource: str
    cluster: str
    namespace: str
    name: str
    object: dict
    rv: int
    old_object: dict | None = None  # prior state on MODIFIED/DELETED

    @property
    def key(self) -> Key:
        return (self.resource, self.cluster, self.namespace, self.name)


class Watch:
    """A filtered subscription to store events.

    Sync consumers call :meth:`drain`; async consumers iterate with
    ``async for``. Closing is idempotent.
    """

    def __init__(
        self,
        store: "LogicalStore",
        resource: str,
        cluster: str,
        namespace: str | None,
        selector: LabelSelector,
    ):
        self._store = store
        self.resource = resource
        self.cluster = cluster
        self.namespace = namespace
        self.selector = selector
        self._events: deque[Event] = deque()
        self._closed = False
        # backpressure policy (KCP_WATCH_QUEUE): a consumer that stops
        # draining past the bound gets evicted instead of pinning the
        # window in unbounded per-watcher memory; `evicted` tells the
        # serving layer to end the stream with a typed 410 rather than
        # a silent close
        self._max_queue = store._watch_queue
        self.evicted = False
        self._wakeup: asyncio.Event | None = None
        # batched fan-out (indexed stores): a single-equality selector
        # matches via one interned pair id (the fanout_match shape), a
        # general kernel-shaped one via a CompiledSelector; both None =>
        # exact per-event python matching (_transform)
        self._eq_pid: int | None = None
        self._compiled = None

    def _scope_match(self, ev: Event) -> bool:
        if ev.resource != self.resource:
            return False
        if self.cluster != WILDCARD and ev.cluster != self.cluster:
            return False
        return self.namespace is None or ev.namespace == self.namespace

    @staticmethod
    def _labels(obj: dict | None) -> dict:
        return ((obj or {}).get("metadata") or {}).get("labels") or {}

    def _transform(self, ev: Event) -> Event | None:
        """Filter/rewrite an event for this watch's selector.

        Kubernetes apiserver semantics for selector-bound watches: an
        object whose labels *stop* matching surfaces as DELETED (so caches
        evict it), one whose labels *start* matching on an update surfaces
        as ADDED. Without this, selector-bound informer caches go
        permanently stale on label transitions.
        """
        if not self._scope_match(ev):
            return None
        if self.selector.empty:
            return ev
        new_match = ev.type != DELETED and self.selector.matches(self._labels(ev.object))
        old_match = self.selector.matches(self._labels(ev.old_object))
        if ev.type == ADDED:
            return ev if new_match else None
        if ev.type == DELETED:
            return ev if old_match or new_match else None
        if new_match and old_match:
            return ev
        if new_match:
            return Event(ADDED, ev.resource, ev.cluster, ev.namespace, ev.name,
                         ev.object, ev.rv, ev.old_object)
        if old_match:
            return Event(DELETED, ev.resource, ev.cluster, ev.namespace, ev.name,
                         ev.object, ev.rv, ev.old_object)
        return None

    def _push(self, ev: Event) -> None:
        if self._closed:
            return
        if should_drop("watch"):
            # injected stream loss (KCP_FAULTS `watch:drop...`): the event
            # is lost and the watch dies mid-stream, exactly like a
            # dropped connection — consumers must re-list (informers do)
            self.close()
            return
        if should_drop("watch.evict") or (
                self._max_queue and len(self._events) >= self._max_queue):
            # queue overflow (or an injected eviction drill): this
            # consumer is too slow to keep its seat — evict it rather
            # than buffer without bound. The event is NOT appended: the
            # stream ends with a typed 410 and the client relists.
            self._evict()
            return
        self._events.append(ev)
        depth = len(self._events)
        if depth >= 64 and depth & (depth - 1) == 0:
            # sampled at powers of two: queue depth visibility without a
            # histogram transaction on every push of the hot path
            self._store._queue_depth.observe(depth)
        if self._wakeup is not None:
            self._wakeup.set()

    def _evict(self) -> None:
        self.evicted = True
        self._store._evicted_total.inc()
        log.warning(
            "watch %s/%s evicted: consumer fell %d events behind "
            "(KCP_WATCH_QUEUE=%d)", self.resource, self.cluster,
            len(self._events), self._max_queue)
        self.close()

    def drain(self) -> list[Event]:
        """Return and clear all buffered events (sync consumers/tests)."""
        self._store._flush_events()
        out = list(self._events)
        self._events.clear()
        if self._wakeup is not None:
            self._wakeup.clear()
        return out

    def pending(self) -> int:
        self._store._flush_events()
        return len(self._events)

    def close(self) -> None:
        if not self._closed:
            # deliver what was emitted before the close — with deferred
            # fan-out, an event committed pre-close must still land in
            # this watch's buffer (legacy _emit delivered synchronously)
            self._store._flush_events()
            self._closed = True
            self._store._unsubscribe(self)
            if self._wakeup is not None:
                self._wakeup.set()

    @property
    def closed(self) -> bool:
        return self._closed

    def __aiter__(self) -> "Watch":
        return self

    async def __anext__(self) -> Event:
        while True:
            self._store._flush_events()
            if self._events:
                return self._events.popleft()
            if self._closed:
                raise StopAsyncIteration
            if self._wakeup is None:
                self._wakeup = asyncio.Event()
            self._wakeup.clear()
            await self._wakeup.wait()

    async def next_batch(self, max_wait: float = 0.05) -> list[Event]:
        """Await at least one event (or closure), then drain the buffer.

        The batching primitive for the TPU backend: the reconcile tick
        collects a delta batch instead of handling events one at a time.
        """
        self._store._flush_events()
        if not self._events and not self._closed:
            if self._wakeup is None:
                self._wakeup = asyncio.Event()
            self._wakeup.clear()
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout=max_wait)
            except asyncio.TimeoutError:
                pass
        return self.drain()


@dataclass
class _WalConfig:
    path: str
    fh: Any = None
    mutations_since_snapshot: int = 0
    snapshot_every: int = 50_000


def _wal_key(key: Key) -> bytes:
    """NUL-joined key tuple: ordered by (resource, cluster, ns, name) so
    native prefix scans follow the etcd range-scan idiom."""
    return "\x00".join(key).encode("utf-8")


_WAL_MAGIC = b"KCPWAL1\n"  # stamped by native/walstore.cc on every file


def _inject(point: str) -> None:
    """KCP_FAULTS injection for a store verb: may raise an injected 503
    (UnavailableError) or sleep an injected latency. Near-free when no
    injector is active."""
    delay = maybe_fail(point)
    if delay:
        time.sleep(delay)


def _detect_wal_format(path: str) -> str | None:
    """Detect an existing WAL's format: "json" (JSON-lines), "native"
    (binary, identified by its magic header), or None (absent/empty).

    The magic header is authoritative — a binary record length whose low
    byte happens to be 0x7B ('{') must never read as JSON. JSON-lines
    files (which always start with ``{"op":`` or a ``{`` snapshot) are
    recognized explicitly; any other nonempty content is treated as
    native so the engine's CRC replay (which tolerates legacy
    magic-less files) gets to decide.
    """
    for candidate in (path, path + ".snap"):
        try:
            with open(candidate, "rb") as f:
                head = f.read(len(_WAL_MAGIC))
        except OSError:
            continue
        if not head:
            continue
        if head == _WAL_MAGIC:
            return "native"
        return "json" if head.lstrip()[:1] == b"{" else "native"
    return None


class LogicalStore:
    """The multi-tenant object store + watch hub."""

    def __init__(
        self,
        wal_path: str | None = None,
        clock: Callable[[], float] = time.time,
        wal_backend: str = "auto",
        wal_sync_every: int = 256,
        namespace_lifecycle: bool = False,
        indexed: bool | None = None,
        encode_cache: bool | None = None,
    ):
        """``indexed``: None reads ``KCP_STORE_INDEX`` (default on) —
        False keeps the pre-index linear-scan/deepcopy read path and the
        per-watch python fan-out for A/B measurement.

        ``encode_cache``: None reads ``KCP_ENCODE_CACHE`` (default on) —
        False keeps per-call ``json.dumps`` serving for A/B
        (``bench.py --encode``). Only effective on indexed stores: the
        cache's validity rests on the CoW snapshot contract, which the
        legacy deepcopy-per-read path does not provide.

        ``wal_backend``: "auto" uses the native C++ engine
        (native/walstore.cc — binary records, CRC32 torn-write recovery,
        batched fsync) when the library loads, else the JSON-lines
        fallback; "native"/"json" force a choice.

        ``namespace_lifecycle``: stamp the ``kubernetes`` finalizer on
        namespaces at create (admission-style). Only enable where a
        NamespaceLifecycleController will actually release it — the kcp
        server does; bare stores and physical-cluster fakes must not,
        or their namespaces can never finish deleting.
        """
        self.namespace_lifecycle = namespace_lifecycle
        # Attachable /openapi/v2 (swagger) document for this store's
        # API surface — the discovery metadata the CRD puller's schema
        # synthesis consumes (reference: kube-openapi models fed into
        # SchemaConverter, pkg/crdpuller/discovery.go:190-207). Not
        # persisted: it is serving metadata, not state.
        self.openapi_doc: dict | None = None
        # race detection (KCP_RACE=1, the `go test -race` analog): the
        # store is loop-owned single-threaded state — every mutation
        # asserts it runs on the owning thread (utils/raceguard.py)
        from ..utils.raceguard import AffinityGuard

        self._race_guard = AffinityGuard("LogicalStore")
        # runtime sanitizer (KCP_SANITIZE=1): stored snapshots freeze
        # (mutation raises at the violating line) and the encode caches
        # verify every hit against a fresh encode — the crash-loudly
        # twin of the CoW/frozen-bytes lint contracts
        self._sanitize = _sanitize.enabled()
        # admission quota accounting: called (resource, cluster, +1/-1)
        # whenever the object map gains/loses a key — the mutation-level
        # usage hook the QuotaLedger attaches (admission/quota.py). None
        # (the default) is one attribute read per mutation.
        self._usage_hook = None
        # replication hook: called with every committed WAL record dict
        # (both durability backends and in-memory stores alike) — the
        # primary-side ReplicationHub attaches here to ship the log.
        self._repl_hook = None
        # read-only stores (replicas, standbys pre-promotion, fenced
        # zombie primaries) refuse mutating verbs with a 503; None means
        # writable, a string carries the human-readable reason. Fenced
        # rejections are additionally counted (repl_fenced_writes_total).
        self.read_only: str | None = None
        self.fenced = False
        # replication epoch: bumped on standby promotion and stamped on
        # every shipped stream so a superseded primary's late records
        # are rejected. Persisted with the WAL (epoch record / snapshot
        # field / native OP_EPOCH) so a restart cannot rewind the fence.
        self.epoch = 0
        # RV honesty for replicas: a watch resume beyond the applied RV
        # is knowledge this store does not have — with this flag set the
        # watch answers a typed 410 instead of silently subscribing
        # "live" at a point the client is already past.
        self.reject_future_rv = False
        # elastic scale-out (sharding/migrate.py): per-cluster write
        # fences (cluster -> cutover RV) held while that cluster's data
        # streams to its new owning shard, and per-cluster RV floors on
        # the RECEIVING shard (cluster -> first post-migration RV) so a
        # resume carrying a source-shard RV answers a typed 410 instead
        # of silently resuming against an unrelated RV history.
        self._cluster_fences: dict[str, int] = {}
        self._migration_floors: dict[str, int] = {}
        self._objects: dict[Key, dict] = {}
        self._rv = 0
        self._watches: list[Watch] = []
        # watch hub index: resource -> live watches, maintained on
        # subscribe/unsubscribe with a version stamp per resource so the
        # fan-out's per-watch scope/selector arrays are built once per
        # watch-set change, not once per flush (at 10k watchers the
        # per-flush rebuild WAS the fan-out cost)
        self._watches_by_res: dict[str, list[Watch]] = {}
        self._watch_ver: dict[str, int] = {}
        self._fanout_cache: dict[str, tuple] = {}
        # the watch-cache window (KCP_WATCH_WINDOW events): both the
        # resume source and the bound on how far back since_rv may reach
        self._history: deque[Event] = deque(maxlen=_env_watch_window())
        # shared resume window: a bisect-able mirror of _history (event
        # refs + their rvs, compacted lazily) so a reconnect storm of N
        # watchers resuming from nearby rvs costs N binary searches over
        # ONE shared index instead of N independent tail-scans. The
        # mirror self-heals against direct _history surgery (tests shrink
        # or swap the deque): a cheap end-identity check at resume time
        # rebuilds it when out of sync.
        self._hist_events: list[Event] = []
        self._hist_rvs: list[int] = []
        self._hist_start = 0
        self._watch_queue = _env_watch_queue()
        self._clock = clock
        self._indexed = _env_indexed() if indexed is None else bool(indexed)
        # secondary index: resource -> cluster -> namespace -> {key: obj};
        # maintained on every mutation (both modes — clusters()/
        # resources()/locate() read it), pruned empty so the bucket keys
        # are exactly the live (resource, cluster, namespace) triples
        self._buckets: dict[str, dict[str, dict[str, dict[Key, dict]]]] = {}
        # batched watch fan-out (indexed mode)
        self._pending: list[Event] = []
        self._flush_scheduled = False
        self._flushing = False
        self._emit_batch = max(1, int(os.environ.get("KCP_STORE_EMIT_BATCH", "128")))
        # exact label interning for the vectorized matchers: distinct
        # (key, value) pairs / keys get sequential nonzero uint32 ids, so
        # unlike the device kernels' 32-bit hashes two labels can never
        # alias — watch semantics stay byte-identical to _transform
        self._intern_pairs: dict = {}
        self._intern_keys: dict[str, int] = {}
        self._labelmatch = None  # lazy ops.labelmatch module (pulls jax)
        # encode-once byte cache: id(snapshot) -> (snapshot, bytes). The
        # entry holds a strong ref to its snapshot, so a live id can
        # never be reused by a different object — presence implies
        # identity. Mutation replaces the snapshot (CoW), which is the
        # whole invalidation story; _put_obj/_del_obj evict replaced
        # snapshots purely to bound memory to the live object set.
        self._encode_cache = (_env_encode_cache() if encode_cache is None
                              else bool(encode_cache)) and self._indexed
        self._enc_bytes: dict[int, tuple[dict, bytes]] = {}
        # per-bucket list spans: (resource, cluster, namespace) ->
        # (bucket version, b", ".join of the bucket's sorted item
        # bytes). A mutation bumps the bucket's version, so an
        # unselected list re-joins only the buckets that changed and
        # concatenates the rest — no global sort, no per-item probe.
        self._span_cache: dict[tuple[str, str, str], tuple[int, bytes]] = {}
        self._bucket_ver: dict[tuple[str, str, str], int] = {}
        self._enc_hits = REGISTRY.counter(
            "encode_cache_hits_total",
            "serializations served from the encode-once byte cache")
        self._enc_misses = REGISTRY.counter(
            "encode_cache_misses_total",
            "serializations that had to run json.dumps")
        self._enc_shared = REGISTRY.counter(
            "encode_cache_bytes_shared_total",
            "response bytes served from cached encodings")
        self._resume_shared = REGISTRY.counter(
            "watch_resume_shared_total",
            "watch resumes answered from the shared in-sync window index "
            "(one bisect, no per-watcher history scan)")
        self._evicted_total = REGISTRY.counter(
            "watch_evicted_total",
            "watchers evicted for falling behind (per-watcher queue "
            "overflow or socket buffer past KCP_WATCH_BUFFER_MAX)")
        self._queue_depth = REGISTRY.histogram(
            "watch_queue_depth",
            "per-watcher buffered events, sampled at powers of two >= 64",
            buckets=SIZE_BUCKETS)
        # global cluster/namespace interning for the fan-out scope
        # matrices: ids are stable across batches, so the per-watch
        # scope arrays can be cached per watch-set version instead of
        # re-interned against every batch
        self._intern_cl: dict[str, int] = {}
        self._intern_ns: dict[str, int] = {}
        self._wal: _WalConfig | None = None
        self._engine = None
        self._engine_mutations = 0
        self._engine_snapshot_every = 50_000
        # WAL sync policy (KCP_WAL_SYNC=flush|fsync|off): read before the
        # engine opens — fsync/off take over sync scheduling explicitly,
        # so the engine's own sync_every batching is disabled for them
        self._wal_sync = _env_wal_sync()
        # group commit (KCP_GROUP_COMMIT, default on): concurrent
        # mutations coalesce into a bounded commit window that appends as
        # ONE buffered write + ONE sync, ships ONE replication batch, and
        # fires ONE watch fan-out flush. Windows only form on stores with
        # a sink (WAL or replication hook) under a running event loop;
        # sync-context callers keep the serial path record for record.
        self._gc_enabled = _env_group_commit()
        self._gc_max = _env_commit_window_max()
        self._gc_linger_s = _env_commit_window_us() / 1e6
        self._gc_window: _CommitWindow | None = None
        self._gc_windows_total = REGISTRY.counter(
            "store_commit_windows_total",
            "group-commit windows flushed (one WAL append + one sync + "
            "one replication batch + one fan-out flush each)")
        self._gc_window_size = REGISTRY.histogram(
            "store_commit_window_size",
            "mutations coalesced per group-commit window",
            buckets=SIZE_BUCKETS)
        self._wal_sync_total = REGISTRY.counter(
            "wal_sync_total",
            "explicit WAL flush/fsync operations (KCP_WAL_SYNC policy); "
            "group commit amortizes these across a whole window")
        self._wal_sync_seconds = REGISTRY.histogram(
            "wal_sync_seconds",
            "time spent in one WAL durable append + flush/fsync call")
        # batched replication hook: set alongside _repl_hook — a flushed
        # window ships once through this instead of once per record
        self._repl_batch = None
        if wal_backend not in ("auto", "native", "json"):
            raise InvalidError(f"unknown wal_backend {wal_backend!r} (auto|native|json)")
        if wal_path:
            existing = _detect_wal_format(wal_path)
            if wal_backend == "auto":
                # never reinterpret an existing WAL under a different
                # format — the native engine would truncate a JSON WAL as
                # a torn tail and destroy it
                use_native = existing != "json"
            elif wal_backend == "native":
                if existing == "json":
                    raise InvalidError(
                        f"{wal_path} holds a JSON-lines WAL; migrate it (load with "
                        f"wal_backend='json', snapshot to a fresh path) before "
                        f"forcing the native engine"
                    )
                use_native = True
            else:
                if existing == "native":
                    raise InvalidError(
                        f"{wal_path} holds a native binary WAL; it cannot be "
                        f"opened with wal_backend='json'"
                    )
                use_native = False
            if use_native:
                try:
                    from ..native import WalEngine

                    # flush (default) keeps the engine's legacy batched
                    # fsync; fsync/off schedule syncs explicitly (per
                    # record / per window / never), so the engine's own
                    # sync_every counter is disabled for them
                    eng_sync = (wal_sync_every
                                if self._wal_sync == "flush" else 0)
                    self._engine = WalEngine(wal_path, sync_every=eng_sync)
                except Exception:
                    if wal_backend == "native":
                        raise
                    if existing == "native":
                        raise  # a binary WAL is unreadable without the engine
            if self._engine is not None:
                self._load_engine()
            else:
                self._wal = _WalConfig(path=wal_path)
                self._load_wal()
                self._wal.fh = open(wal_path, "a", encoding="utf-8")

    # ------------------------------------------------------------------ RV

    @property
    def resource_version(self) -> int:
        return self._rv

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # ------------------------------------------------------------- helpers

    @staticmethod
    def _key(resource: str, cluster: str, namespace: str, name: str) -> Key:
        if not resource or not cluster or not name:
            raise InvalidError("resource, cluster and name are required")
        if cluster == WILDCARD:
            raise InvalidError("wildcard cluster is read-only")
        return (resource, cluster, namespace or "", name)

    @staticmethod
    def _meta(obj: Mapping) -> dict:
        return obj.get("metadata") or {}

    def _now(self) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self._clock()))

    # ------------------------------------------------------------- index

    def _put_obj(self, key: Key, obj: dict) -> dict:
        """Insert/replace an object in the map and the secondary index.
        Returns the stored snapshot — under the sanitizer it is a frozen
        proxy, and callers emit/log THAT object so events keep sharing
        the stored snapshot's identity."""
        if self._sanitize:
            obj = _sanitize.freeze(obj)
        old = self._objects.get(key)
        if self._usage_hook is not None and old is None:
            self._usage_hook(key[0], key[1], 1)
        if self._encode_cache:
            if old is not None and self._enc_bytes:
                # memory hygiene only: the replaced snapshot's cached
                # bytes can never be served again (identity mismatch)
                self._enc_bytes.pop(id(old), None)
            bk = key[:3]
            self._bucket_ver[bk] = self._bucket_ver.get(bk, 0) + 1
        self._objects[key] = obj
        r, c, n, _ = key
        self._buckets.setdefault(r, {}).setdefault(c, {}).setdefault(n, {})[key] = obj
        return obj

    def _del_obj(self, key: Key) -> None:
        old = self._objects.get(key)
        if old is not None:
            if self._usage_hook is not None:
                self._usage_hook(key[0], key[1], -1)
            if self._encode_cache:
                self._enc_bytes.pop(id(old), None)
                bk = key[:3]
                self._bucket_ver[bk] = self._bucket_ver.get(bk, 0) + 1
        self._objects.pop(key, None)
        r, c, n, _ = key
        res = self._buckets.get(r)
        if res is None:
            return
        cl = res.get(c)
        if cl is None:
            return
        ns = cl.get(n)
        if ns is None:
            return
        ns.pop(key, None)
        if not ns:
            self._span_cache.pop(key[:3], None)
            del cl[n]
            if not cl:
                del res[c]
                if not res:
                    del self._buckets[r]

    def locate(self, resource: str, name: str, namespace: str = "") -> list[str]:
        """Clusters holding (resource, namespace, name) — the index-driven
        answer to wildcard single-object reads (server.handler scans
        tenants for the unique owner)."""
        ns = namespace or ""
        out = []
        for c, nss in self._buckets.get(resource, {}).items():
            if (resource, c, ns, name) in nss.get(ns, ()):
                out.append(c)
        return sorted(out)

    # --------------------------------------------------------------- CRUD

    def _check_writable(self) -> None:
        """Refuse mutations on read-only stores (replicas, unpromoted
        standbys, fenced ex-primaries). 503 rather than 403: informers
        and retrying clients treat it as a routing problem — the write
        belongs on the current primary — not a policy denial."""
        if self.read_only is not None:
            if self.fenced:
                REGISTRY.counter(
                    "repl_fenced_writes_total",
                    "writes refused because this store was fenced by a "
                    "newer replication epoch").inc()
            raise UnavailableError(f"store is read-only: {self.read_only}")

    def _check_cluster_writable(self, cluster: str) -> None:
        """Refuse writes to a cluster whose migration cutover is in
        progress. 503 like the store-wide fence: the write belongs on
        the cluster's NEW owner — clients retry, and by the time they
        do the ring has flipped (the fence window is one WAL stream)."""
        cut = self._cluster_fences.get(cluster)
        if cut is not None:
            REGISTRY.counter(
                "migration_fenced_writes_total",
                "writes refused because the cluster was fenced at its "
                "migration cutover RV (retry lands on the new owner)").inc()
            raise UnavailableError(
                f"cluster {cluster!r} is migrating to a new shard "
                f"(fenced at rv {cut}); retry")

    def _commit_trace(self, tctx, t0: float, key: Key, rv: int,
                      rec: dict, obj: dict | None) -> None:
        """Stamp a sampled write's trace onto its WAL record (``tc``
        rides the replication feed) and link the stored snapshot to the
        committing context (in-process informers resolve causality by
        object identity); records the ``store.commit`` span. One stamp
        covers every watcher/subscriber — the events already carry the
        context (see :meth:`_emit`)."""
        sub = obs.TRACER.child(tctx)
        rec["tc"] = [sub.trace_id, sub.span_id]
        obs.record_span(
            "store.commit", sub, tctx.span_id, t0, time.time() - t0,
            {"resource": key[0], "cluster": key[1], "name": key[3],
             "rv": str(rv), "op": rec["op"]})
        if obj is not None:
            obs.link_obj(obj, sub)

    def create(self, resource: str, cluster: str, obj: dict, namespace: str = "") -> dict:
        self._race_guard.check()
        self._check_writable()
        self._check_cluster_writable(cluster)
        tctx = obs.write_ctx()
        t0 = time.time() if tctx is not None else 0.0
        _inject("store.put")
        obj = copy.deepcopy(obj)
        meta = obj.setdefault("metadata", {})
        name = meta.get("name")
        if not name:
            if meta.get("generateName"):
                name = meta["generateName"] + uuid.uuid4().hex[:6]
                meta["name"] = name
            else:
                raise InvalidError("metadata.name is required")
        namespace = namespace or meta.get("namespace") or ""
        key = self._key(resource, cluster, namespace, name)
        if key in self._objects:
            raise AlreadyExistsError(f"{resource} {cluster}/{namespace}/{name} already exists")
        if resource == "namespaces" and self.namespace_lifecycle:
            # admission-style lifecycle finalizer, stamped synchronously at
            # create (as the real apiserver's NamespaceLifecycle admission
            # does) so a create+delete race can never skip the content
            # sweep in reconcilers/namespace.py
            fins = meta.setdefault("finalizers", [])
            if "kubernetes" not in fins:
                fins.append("kubernetes")
        meta["namespace"] = namespace
        meta["clusterName"] = cluster
        meta["uid"] = meta.get("uid") or str(uuid.uuid4())
        meta["creationTimestamp"] = self._now()
        meta["generation"] = 1
        rv = self._next_rv()
        meta["resourceVersion"] = str(rv)
        obj = self._put_obj(key, obj)
        self._emit(ADDED, key, obj, rv, tc=tctx)
        rec = {"op": "put", "key": list(key), "obj": obj, "rv": rv}
        if tctx is not None:
            self._commit_trace(tctx, t0, key, rv, rec, obj)
        self._log_wal(rec)
        return copy.deepcopy(obj)

    def get(self, resource: str, cluster: str, name: str, namespace: str = "") -> dict:
        _inject("store.get")
        key = self._key(resource, cluster, namespace, name)
        obj = self._objects.get(key)
        if obj is None:
            raise NotFoundError(f"{resource} {cluster}/{namespace}/{name} not found")
        return copy.deepcopy(obj)

    def get_snapshot(self, resource: str, cluster: str, name: str,
                     namespace: str = "") -> dict:
        """The stored snapshot itself, no copy — the CoW read for encode
        paths (callers must not mutate the result; mutators start from
        :meth:`get`). Fault-injected exactly like :meth:`get` so cached
        and uncached serving fail identically under KCP_FAULTS."""
        _inject("store.get")
        key = self._key(resource, cluster, namespace, name)
        obj = self._objects.get(key)
        if obj is None:
            raise NotFoundError(f"{resource} {cluster}/{namespace}/{name} not found")
        return obj

    def update(
        self,
        resource: str,
        cluster: str,
        obj: dict,
        namespace: str = "",
        subresource: str | None = None,
    ) -> dict:
        self._race_guard.check()
        self._check_writable()
        self._check_cluster_writable(cluster)
        tctx = obs.write_ctx()
        t0 = time.time() if tctx is not None else 0.0
        _inject("store.put")
        obj = copy.deepcopy(obj)
        meta = self._meta(obj)
        name = meta.get("name")
        if not name:
            raise InvalidError("metadata.name is required")
        namespace = namespace or meta.get("namespace") or ""
        key = self._key(resource, cluster, namespace, name)
        existing = self._objects.get(key)
        if existing is None:
            raise NotFoundError(f"{resource} {cluster}/{namespace}/{name} not found")
        ex_meta = existing["metadata"]
        supplied_rv = meta.get("resourceVersion")
        if supplied_rv and supplied_rv != ex_meta["resourceVersion"]:
            raise ConflictError(
                f"{resource} {cluster}/{namespace}/{name}: stale resourceVersion "
                f"{supplied_rv} (current {ex_meta['resourceVersion']})"
            )
        if subresource == "status":
            new_obj = copy.deepcopy(existing)
            new_obj["status"] = obj.get("status")
        else:
            new_obj = obj
            # status is only writable through the status subresource
            if "status" in existing:
                new_obj["status"] = copy.deepcopy(existing["status"])
            elif "status" in new_obj:
                del new_obj["status"]
        new_meta = new_obj.setdefault("metadata", {})
        if subresource != "status":
            # metadata edits (labels/annotations/finalizers) ride spec updates
            preserved = {
                "uid": ex_meta.get("uid"),
                "creationTimestamp": ex_meta.get("creationTimestamp"),
                "clusterName": cluster,
                "namespace": namespace,
                "name": name,
            }
            new_meta.update(preserved)
            if ex_meta.get("deletionTimestamp"):
                new_meta["deletionTimestamp"] = ex_meta["deletionTimestamp"]
        else:
            new_obj["metadata"] = copy.deepcopy(ex_meta)
            new_meta = new_obj["metadata"]

        spec_changed = subresource != "status" and self._non_status_changed(existing, new_obj)
        new_meta["generation"] = ex_meta.get("generation", 1) + (1 if spec_changed else 0)
        rv = self._next_rv()
        new_meta["resourceVersion"] = str(rv)
        new_obj = self._put_obj(key, new_obj)

        # finalizer-driven deletion completion
        if new_meta.get("deletionTimestamp") and not new_meta.get("finalizers"):
            self._del_obj(key)
            self._emit(DELETED, key, new_obj, rv, old=existing, tc=tctx)
            rec = {"op": "del", "key": list(key), "rv": rv}
            if tctx is not None:
                self._commit_trace(tctx, t0, key, rv, rec, None)
            self._log_wal(rec)
        else:
            self._emit(MODIFIED, key, new_obj, rv, old=existing, tc=tctx)
            rec = {"op": "put", "key": list(key), "obj": new_obj, "rv": rv}
            if tctx is not None:
                self._commit_trace(tctx, t0, key, rv, rec, new_obj)
            self._log_wal(rec)
        return copy.deepcopy(new_obj)

    def update_status(self, resource: str, cluster: str, obj: dict, namespace: str = "") -> dict:
        return self.update(resource, cluster, obj, namespace, subresource="status")

    def delete(self, resource: str, cluster: str, name: str, namespace: str = "") -> None:
        self._race_guard.check()
        self._check_writable()
        self._check_cluster_writable(cluster)
        tctx = obs.write_ctx()
        t0 = time.time() if tctx is not None else 0.0
        _inject("store.delete")
        key = self._key(resource, cluster, namespace, name)
        existing = self._objects.get(key)
        if existing is None:
            raise NotFoundError(f"{resource} {cluster}/{namespace}/{name} not found")
        meta = existing["metadata"]
        if meta.get("finalizers"):
            if not meta.get("deletionTimestamp"):
                obj = copy.deepcopy(existing)
                obj["metadata"]["deletionTimestamp"] = self._now()
                rv = self._next_rv()
                obj["metadata"]["resourceVersion"] = str(rv)
                obj = self._put_obj(key, obj)
                self._emit(MODIFIED, key, obj, rv, old=existing, tc=tctx)
                rec = {"op": "put", "key": list(key), "obj": obj, "rv": rv}
                if tctx is not None:
                    self._commit_trace(tctx, t0, key, rv, rec, obj)
                self._log_wal(rec)
            return
        self._del_obj(key)
        rv = self._next_rv()
        self._emit(DELETED, key, existing, rv, old=existing, tc=tctx)
        rec = {"op": "del", "key": list(key), "rv": rv}
        if tctx is not None:
            self._commit_trace(tctx, t0, key, rv, rec, None)
        self._log_wal(rec)

    # --------------------------------------------------------------- list

    def list(
        self,
        resource: str,
        cluster: str = WILDCARD,
        namespace: str | None = None,
        selector: LabelSelector | None = None,
    ) -> tuple[list[dict], int]:
        """Return (items, list resourceVersion).

        Indexed mode walks only the (resource, cluster, namespace)
        candidate buckets and returns shared references (CoW contract:
        callers must not mutate items — re-``get`` or deepcopy before
        editing). Legacy mode is the pre-index O(total-objects) scan
        with a deepcopy per match.
        """
        _inject("store.list")
        selector = selector or everything()
        if not self._indexed:
            out = []
            for (res, cl, ns, _name), obj in self._objects.items():
                if res != resource:
                    continue
                if cluster != WILDCARD and cl != cluster:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                labels = (obj.get("metadata") or {}).get("labels") or {}
                if not selector.matches(labels):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: (o["metadata"].get("clusterName", ""),
                                    o["metadata"].get("namespace", ""),
                                    o["metadata"]["name"]))
            self._list_metrics(len(self._objects), len(out))
            return out, self._rv

        scanned = 0
        pairs: list[tuple[Key, dict]] = []
        res_b = self._buckets.get(resource)
        if res_b:
            if cluster != WILDCARD:
                cl_bs = [res_b[cluster]] if cluster in res_b else []
            else:
                cl_bs = list(res_b.values())
            empty = selector.empty
            for cl_b in cl_bs:
                if namespace is not None:
                    ns_bs = [cl_b[namespace]] if namespace in cl_b else []
                else:
                    ns_bs = list(cl_b.values())
                for ns_b in ns_bs:
                    scanned += len(ns_b)
                    if empty:
                        pairs.extend(ns_b.items())
                    else:
                        for key, obj in ns_b.items():
                            labels = (obj.get("metadata") or {}).get("labels") or {}
                            if selector.matches(labels):
                                pairs.append((key, obj))
        # key order == metadata (clusterName, namespace, name) order: the
        # key IS the metadata triple (resource is constant here and keys
        # are unique, so the dicts never get compared), and the bare
        # tuple sort stays in C — no per-element key lambda
        pairs.sort()
        out = [obj for _, obj in pairs]
        self._list_metrics(scanned, len(out))
        return out, self._rv

    @staticmethod
    def _list_metrics(scanned: int, returned: int) -> None:
        REGISTRY.counter("store_list_scanned_total",
                         "objects examined by store list scans").inc(scanned)
        REGISTRY.counter("store_list_returned_total",
                         "objects returned by store lists").inc(returned)

    def set_usage_hook(self, hook) -> None:
        """Install the per-mutation usage callback
        ``hook(resource, cluster, delta)`` (admission quota ledger)."""
        self._usage_hook = hook

    def counts(self) -> dict[tuple[str, str], int]:
        """Object counts per (resource, cluster) from the secondary
        index — the naive full recount the quota ledger reconciles
        against (bucket lengths only, no object walk)."""
        return {
            (r, c): sum(len(ns) for ns in cl.values())
            for r, res in self._buckets.items()
            for c, cl in res.items()
        }

    def resources(self) -> list[str]:
        """Distinct resource names present in the store."""
        return sorted(self._buckets)

    def clusters(self) -> list[str]:
        """Distinct logical-cluster names present in the store."""
        return sorted({c for res in self._buckets.values() for c in res})

    def __len__(self) -> int:
        return len(self._objects)

    # ------------------------------------------------ encode-once serving

    @property
    def encode_cache_enabled(self) -> bool:
        """True when serving paths may splice cached snapshot bytes
        (KCP_ENCODE_CACHE on an indexed/CoW store)."""
        return self._encode_cache

    def encode_obj(self, obj: dict) -> bytes:
        """Default-format JSON bytes of a stored snapshot, computed once
        per snapshot object.

        The bytes are valid for exactly as long as the snapshot object is
        reachable: CoW means a mutation replaces the snapshot, so a stale
        entry can never be looked up again (its id only matches while the
        entry's own strong reference keeps the old object alive). The
        ``encode.cache`` fault point force-drops a cached entry to
        exercise the re-encode fallback.
        """
        if not self._encode_cache:
            return json.dumps(obj).encode()
        ent = self._enc_bytes.get(id(obj))
        if ent is not None and ent[0] is obj:
            if should_drop("encode.cache"):
                del self._enc_bytes[id(obj)]
            else:
                if self._sanitize:
                    _sanitize.verify_bytes(
                        ent[1], json.dumps(obj).encode(), "snapshot bytes")
                self._enc_hits.inc()
                self._enc_shared.inc(len(ent[1]))
                return ent[1]
        data = json.dumps(obj).encode()
        self._enc_misses.inc()
        self._enc_bytes[id(obj)] = (obj, data)
        return data

    def encode_many(self, objs: list[dict]) -> list[bytes]:
        """:meth:`encode_obj` over a list result, with the per-item
        bookkeeping hoisted out of the loop (one counter update per call,
        fault checks only while an injector is active) — the list
        response splice path runs this over 100k items per request."""
        if not self._encode_cache:
            return [json.dumps(o).encode() for o in objs]
        from .. import faults as _faults

        if (_faults._ACTIVE is not None or not _faults._ENV_CHECKED
                or self._sanitize):
            # an active KCP_FAULTS schedule must see one encode.cache
            # decision per entry, exactly like the per-item path — and
            # the sanitizer verifies each hit there
            return [self.encode_obj(o) for o in objs]
        cache = self._enc_bytes
        dumps = json.dumps
        out: list[bytes] = []
        hits = misses = shared = 0
        for o in objs:
            ent = cache.get(id(o))
            if ent is not None and ent[0] is o:
                data = ent[1]
                hits += 1
                shared += len(data)
            else:
                data = dumps(o).encode()
                cache[id(o)] = (o, data)
                misses += 1
            out.append(data)
        if hits:
            self._enc_hits.inc(hits)
            self._enc_shared.inc(shared)
        if misses:
            self._enc_misses.inc(misses)
        return out

    def list_encoded(
        self,
        resource: str,
        cluster: str = WILDCARD,
        namespace: str | None = None,
    ) -> tuple[list[bytes], int]:
        """Encode-once fast path for *unselected* lists: ``(spans, rv)``
        where each span is one candidate bucket's sorted item bytes
        pre-joined with ``b", "`` — from the per-bucket span caches, so
        an unchanged bucket costs one list append instead of a sort +
        per-item probe (the caller splices spans straight into the
        response envelope with a single join). Scope semantics, result
        ordering, fault injection and list metrics are identical to
        :meth:`list` with an empty selector (bucket keys iterate in
        sorted order, which *is* the global ``(clusterName, namespace,
        name)`` sort — resource is constant and names sort within their
        bucket)."""
        _inject("store.list")
        scanned = 0
        spans: list[bytes] = []
        res_b = self._buckets.get(resource)
        if res_b:
            if cluster != WILDCARD:
                cl_keys = [cluster] if cluster in res_b else []
            else:
                cl_keys = sorted(res_b)
            for c in cl_keys:
                cl_b = res_b[c]
                if namespace is not None:
                    ns_keys = [namespace] if namespace in cl_b else []
                else:
                    ns_keys = sorted(cl_b)
                for n in ns_keys:
                    ns_b = cl_b[n]
                    scanned += len(ns_b)
                    spans.append(self._bucket_span((resource, c, n), ns_b))
        self._list_metrics(scanned, scanned)  # empty selector: all returned
        return spans, self._rv

    def _bucket_span(self, bk: tuple[str, str, str], ns_b: dict) -> bytes:
        from .. import faults as _faults

        ver = self._bucket_ver.get(bk, 0)
        if _faults._ACTIVE is None and _faults._ENV_CHECKED \
                and not self._sanitize:
            ent = self._span_cache.get(bk)
            if ent is not None and ent[0] == ver:
                self._enc_hits.inc()
                self._enc_shared.inc(len(ent[1]))
                return ent[1]
            span = b", ".join(self.encode_many(
                [obj for _, obj in sorted(ns_b.items())]))
            self._span_cache[bk] = (ver, span)
            return span
        # active fault schedule: every entry decision must reach the
        # per-record cache (encode.cache drops), so spans are neither
        # read nor stored
        return b", ".join(self.encode_many(
            [obj for _, obj in sorted(ns_b.items())]))

    # ------------------------------------------- paginated (chunked) lists

    def _page_metrics(self) -> None:
        REGISTRY.counter("list_pages_total",
                         "list pages served (limit/continue chunking)").inc()

    def _check_continue_window(self, rv_pin: int) -> None:
        """A continue token is only honorable while the watch window
        still covers ``(rv_pin, now]`` — the exact bound a watch resume
        uses, because the RV pin is reconstructed from the same retained
        history. Outside it: typed 410, the client re-lists."""
        if rv_pin > self._rv:
            REGISTRY.counter("list_continue_410_total",
                             "continue tokens answered with 410").inc()
            raise GoneError(
                f"continue token rv {rv_pin} is ahead of this store's "
                f"rv {self._rv}; re-list")
        if rv_pin < self._rv:
            oldest = self._history[0].rv if self._history else None
            if oldest is None or oldest > rv_pin + 1:
                REGISTRY.counter("list_continue_410_total",
                                 "continue tokens answered with 410").inc()
                raise GoneError(
                    f"continue token expired: pinned rv {rv_pin}, oldest "
                    f"retained {oldest}; re-list")

    def _pairs_at_pin(
        self,
        resource: str,
        cluster: str,
        namespace: str | None,
        rv_pin: int,
    ) -> list[tuple[Key, dict]]:
        """Sorted scoped ``(key, obj)`` pairs exactly as of ``rv_pin``
        (caller has verified the window covers the gap): start from the
        live buckets and undo retained events newer than the pin, newest
        first — ``old_object`` is the CoW snapshot each event displaced,
        so the rewound objects ARE the objects a list at ``rv_pin``
        returned, byte-cache and all."""
        pairs: dict[Key, dict] = {}
        res_b = self._buckets.get(resource)
        if res_b:
            if cluster != WILDCARD:
                cl_bs = [res_b[cluster]] if cluster in res_b else []
            else:
                cl_bs = list(res_b.values())
            for cl_b in cl_bs:
                if namespace is not None:
                    ns_bs = [cl_b[namespace]] if namespace in cl_b else []
                else:
                    ns_bs = list(cl_b.values())
                for ns_b in ns_bs:
                    pairs.update(ns_b)
        if rv_pin < self._rv:
            for ev in reversed(self._resume_slice(rv_pin)):
                if ev.resource != resource:
                    continue
                if cluster != WILDCARD and ev.cluster != cluster:
                    continue
                if namespace is not None and ev.namespace != namespace:
                    continue
                if ev.type == ADDED:
                    pairs.pop(ev.key, None)
                else:  # MODIFIED / DELETED: restore the displaced snapshot
                    if ev.old_object is not None:
                        pairs[ev.key] = ev.old_object
        return sorted(pairs.items())

    def list_page(
        self,
        resource: str,
        cluster: str = WILDCARD,
        namespace: str | None = None,
        selector: LabelSelector | None = None,
        limit: int = 0,
        continue_token: str | None = None,
    ) -> tuple[list[dict], int, str]:
        """KEP-365-style chunked list: ``(items, rv, next_token)``.

        The first page pins the list at the current rv; every
        continuation serves from the state *as of that pin* (rewound via
        the retained watch window), so concatenated pages are exactly
        the one-shot list at the pinned rv no matter what mutated in
        between. A token the window no longer covers answers typed 410.
        With a selector, the continue key is the last *matched* item's
        key — the filtered order is a subsequence of the raw key order,
        so the resume position is still exact.
        """
        _inject("store.list")
        selector = selector or everything()
        if (limit <= 0 and not continue_token) or not self._indexed:
            # no chunking asked for — or the legacy store, which has no
            # CoW history to pin against: serve the one-shot list (no
            # continue, so paging clients fall back cleanly)
            items, rv = self.list(resource, cluster, namespace, selector)
            return items, rv, ""
        last_key: tuple | None = None
        if continue_token:
            try:
                rv_pin, last_key = decode_continue(continue_token)
            except ValueError:
                REGISTRY.counter("list_continue_410_total",
                                 "continue tokens answered with 410").inc()
                raise GoneError("malformed continue token; re-list") \
                    from None
            self._check_continue_window(rv_pin)
        else:
            self._flush_events()
            rv_pin = self._rv
        pairs = self._pairs_at_pin(resource, cluster, namespace, rv_pin)
        boundary = (resource,) + last_key if last_key is not None else None
        out: list[dict] = []
        scanned = 0
        next_token = ""
        last_included: Key | None = None
        empty = selector.empty
        for key, obj in pairs:
            if boundary is not None and key <= boundary:
                continue
            scanned += 1
            if not empty:
                labels = (obj.get("metadata") or {}).get("labels") or {}
                if not selector.matches(labels):
                    continue
            if limit > 0 and len(out) >= limit:
                next_token = encode_continue(rv_pin, last_included[1:])
                break
            out.append(obj)
            last_included = key
        self._list_metrics(scanned, len(out))
        self._page_metrics()
        return out, rv_pin, next_token

    def list_encoded_page(
        self,
        resource: str,
        cluster: str = WILDCARD,
        namespace: str | None = None,
        limit: int = 0,
        continue_token: str | None = None,
    ) -> tuple[list[bytes], int, str]:
        """Encode-once chunked list for *unselected* scopes:
        ``(spans, rv, next_token)``. The current-rv page walks the
        sorted buckets and splices whole cached :meth:`_bucket_span`
        entries for every fully-included bucket, encoding only the
        boundary slices — a page over unchanged buckets costs list
        appends, not encodes. Pinned-in-the-past pages rewind through
        the watch window like :meth:`list_page`; the rewound snapshots
        still hit the per-object byte cache, so pages stay
        byte-identical to the one-shot body at the pinned rv."""
        _inject("store.list")
        if limit <= 0 and not continue_token:
            spans, rv = self.list_encoded(resource, cluster, namespace)
            return spans, rv, ""
        last_key: tuple | None = None
        if continue_token:
            try:
                rv_pin, last_key = decode_continue(continue_token)
            except ValueError:
                REGISTRY.counter("list_continue_410_total",
                                 "continue tokens answered with 410").inc()
                raise GoneError("malformed continue token; re-list") \
                    from None
            self._check_continue_window(rv_pin)
        else:
            self._flush_events()
            rv_pin = self._rv
        if rv_pin == self._rv:
            return self._encoded_page_current(
                resource, cluster, namespace, limit, last_key, rv_pin)
        pairs = self._pairs_at_pin(resource, cluster, namespace, rv_pin)
        if last_key is not None:
            boundary = (resource,) + last_key
            pairs = [p for p in pairs if p[0] > boundary]
        page = pairs[:limit] if limit > 0 else pairs
        # per-item spans, never a page-wide join: the envelope's parts
        # join (one allocation, at send) is the only materialization
        spans = self.encode_many([o for _, o in page]) if page else []
        next_token = ""
        if limit > 0 and len(pairs) > limit:
            k = page[-1][0]
            next_token = encode_continue(rv_pin, k[1:])
        self._list_metrics(len(page), len(page))
        self._page_metrics()
        return spans, rv_pin, next_token

    def _encoded_page_current(
        self,
        resource: str,
        cluster: str,
        namespace: str | None,
        limit: int,
        last_key: tuple | None,
        rv_pin: int,
    ) -> tuple[list[bytes], int, str]:
        spans: list[bytes] = []
        scanned = 0
        returned = 0
        next_token = ""
        last_included: tuple | None = None
        remaining = limit if limit > 0 else None
        res_b = self._buckets.get(resource)
        buckets: list[tuple[str, str, dict]] = []
        if res_b:
            if cluster != WILDCARD:
                cl_keys = [cluster] if cluster in res_b else []
            else:
                cl_keys = sorted(res_b)
            for c in cl_keys:
                cl_b = res_b[c]
                if namespace is not None:
                    ns_keys = [namespace] if namespace in cl_b else []
                else:
                    ns_keys = sorted(cl_b)
                for n in ns_keys:
                    buckets.append((c, n, cl_b[n]))
        for c, n, ns_b in buckets:
            if not ns_b:
                continue
            if last_key is not None and (c, n) < tuple(last_key[:2]):
                continue  # bucket wholly before the cursor
            items = sorted(ns_b.items())
            whole_bucket = True
            if last_key is not None and (c, n) == tuple(last_key[:2]):
                items = [kv for kv in items if kv[0][3] > last_key[2]]
                whole_bucket = False
                if not items:
                    continue
            if remaining is not None and remaining == 0:
                # page is full and at least one more item exists
                next_token = encode_continue(rv_pin, last_included)
                break
            scanned += len(ns_b)
            if remaining is None or len(items) <= remaining:
                if whole_bucket:
                    # fully-included untouched bucket: splice its cached
                    # span — the same bytes the unpaged path serves
                    spans.append(self._bucket_span((resource, c, n), ns_b))
                else:
                    # boundary slice: per-item cached spans, no join —
                    # the envelope assembles them at send time
                    spans.extend(self.encode_many([o for _, o in items]))
                returned += len(items)
                if remaining is not None:
                    remaining -= len(items)
                last_included = (c, n, items[-1][0][3])
            else:
                take = items[:remaining]
                spans.extend(self.encode_many([o for _, o in take]))
                returned += len(take)
                remaining = 0
                last_included = (c, n, take[-1][0][3])
                # this bucket has more: certainly another page
                next_token = encode_continue(rv_pin, last_included)
                break
        self._list_metrics(scanned, returned)
        self._page_metrics()
        return spans, rv_pin, next_token

    def encode_event(self, ev: Event) -> bytes:
        """The encoded watch wire line ``{"type": ..., "object": ...}\\n``
        for an event, computed once and cached on the event itself — the
        store's batched fan-out pushes the *same* Event instance to every
        matched watch, so 64 relays splice one encoding. Byte-identical
        to ``json.dumps({"type": ev.type, "object": ev.object})``."""
        if self._encode_cache:
            line = ev.__dict__.get("_enc_line")
            if line is not None:
                if should_drop("encode.cache"):
                    object.__setattr__(ev, "_enc_line", None)
                else:
                    if self._sanitize:
                        _sanitize.verify_bytes(
                            line,
                            json.dumps({"type": ev.type,
                                        "object": ev.object}).encode()
                            + b"\n",
                            "watch event line")
                    self._enc_hits.inc()
                    self._enc_shared.inc(len(line))
                    return line
        # DELETED events (and events outlived by later writes) carry a
        # snapshot that is no longer the stored one — encode it without
        # touching the per-record cache, or dead snapshots would pin
        # entries forever. The line cache above still shares the work.
        if self._encode_cache and self._objects.get(ev.key) is ev.object:
            body = self.encode_obj(ev.object)
        else:
            body = json.dumps(ev.object).encode()
            if self._encode_cache:
                self._enc_misses.inc()
        line = (b'{"type": ' + json.dumps(ev.type).encode()
                + b', "object": ' + body + b'}\n')
        if self._encode_cache:
            object.__setattr__(ev, "_enc_line", line)
        return line

    def encode_events(self, evs: list[Event]) -> list[bytes]:
        """:meth:`encode_event` over a relay batch with the per-line
        bookkeeping hoisted out of the loop (the 64-watcher fan-out runs
        this once per watcher per burst — the hit path must cost a dict
        probe, not a metrics transaction)."""
        from .. import faults as _faults

        if (not self._encode_cache or _faults._ACTIVE is not None
                or not _faults._ENV_CHECKED or self._sanitize):
            return [self.encode_event(ev) for ev in evs]
        out: list[bytes] = []
        hits = shared = 0
        for ev in evs:
            line = ev.__dict__.get("_enc_line")
            if line is None:
                line = self.encode_event(ev)  # miss path counts itself
            else:
                hits += 1
                shared += len(line)
            out.append(line)
        if hits:
            self._enc_hits.inc(hits)
            self._enc_shared.inc(shared)
        return out

    # -------------------------------------------------------------- watch

    def watch(
        self,
        resource: str,
        cluster: str = WILDCARD,
        namespace: str | None = None,
        selector: LabelSelector | None = None,
        since_rv: int | None = None,
    ) -> Watch:
        """Subscribe. With ``since_rv``, replays retained history > since_rv."""
        # flush before subscribing: pending events predate this watch and
        # must not be delivered live (the since_rv replay below covers
        # them from history when asked to)
        self._flush_events()
        if (self.reject_future_rv and since_rv is not None
                and since_rv > self._rv):
            # RV-honest replica serving: the caller resumes from a point
            # this store has not applied yet (it read a fresher primary).
            # Never fabricate freshness — typed 410, the client re-lists
            # (or the router retries against the primary).
            raise GoneError(
                f"requested rv {since_rv} is ahead of this replica's "
                f"applied rv {self._rv}; re-list (or read the primary)")
        if since_rv is not None and cluster != WILDCARD:
            floor = self._migration_floors.get(cluster)
            if floor is not None and since_rv < floor:
                # the cluster migrated ONTO this shard at `floor`: any
                # smaller rv was minted by the old owner's independent
                # counter — resuming from it here would be a silent
                # partial resume against an unrelated history. Typed
                # 410: the client re-lists and resumes from local RVs.
                raise GoneError(
                    f"cluster {cluster} migrated onto this shard at rv "
                    f"{floor}; rv {since_rv} predates the move — re-list")
        w = Watch(self, resource, cluster, namespace, selector or everything())
        if self._indexed and not w.selector.empty:
            self._subscribe_selector(w)
        if since_rv is not None and since_rv < self._rv:
            # the retained history must cover (since_rv, now]; otherwise the
            # caller missed events it can never recover (e.g. resuming a
            # pre-restart RV against a WAL-restored store) and must re-list
            oldest = self._history[0].rv if self._history else None
            if oldest is None or oldest > since_rv + 1:
                # typed 410 (GoneError subclasses ConflictError, so the
                # pre-typed except clauses keep working): consumers
                # re-list immediately instead of backoff-retrying
                raise GoneError(
                    f"watch window expired: requested rv {since_rv}, oldest retained {oldest}"
                )
            # shared window resume: one bisect over the window's rv index
            # (shared by every resuming watcher — a 10k-watcher reconnect
            # storm costs 10k binary searches over ONE index, not 10k
            # history scans), replaying the suffix through the watch's
            # own selector transform. The replayed Event objects are the
            # window's own instances, so the encode-once wire bytes are
            # shared across every resumer too.
            for ev in self._resume_slice(since_rv):
                out = w._transform(ev)
                if out is not None:
                    w._push(out)
        if not w._closed:
            # an injected drop/evict during replay already closed (and
            # unregistered) the watch — registering it would leak a dead
            # entry in the hub index
            self._watches.append(w)
            self._watches_by_res.setdefault(resource, []).append(w)
            self._watch_ver[resource] = self._watch_ver.get(resource, 0) + 1
        return w

    def _resume_slice(self, since_rv: int) -> list[Event]:
        """The window events with rv > since_rv, from the shared mirror
        index (rebuilt only when direct history surgery desynced it)."""
        from bisect import bisect_right

        h = self._history
        es, rs, start = self._hist_events, self._hist_rvs, self._hist_start
        live = len(es) - start
        if (live == len(h) and live > 0
                and es[start] is h[0] and es[-1] is h[-1]):
            self._resume_shared.inc()
        else:
            # out of sync (tests swap/shrink the deque; resyncs clear it):
            # rebuild the mirror from the deque once, then bisect
            es = self._hist_events = list(h)
            rs = self._hist_rvs = [e.rv for e in es]
            start = self._hist_start = 0
        return es[bisect_right(rs, since_rv, start):]

    def _note_history(self, ev: Event) -> None:
        """Mirror one appended history event into the shared resume
        index; trims to the deque's live length and compacts lazily."""
        es, rs = self._hist_events, self._hist_rvs
        es.append(ev)
        rs.append(ev.rv)
        excess = (len(es) - self._hist_start) - len(self._history)
        if excess > 0:
            self._hist_start += excess
            if self._hist_start > 65536:
                del es[:self._hist_start]
                del rs[:self._hist_start]
                self._hist_start = 0

    def _emit(self, etype: str, key: Key, obj: dict, rv: int, old: dict | None = None,
              tc=None) -> None:
        if not self._indexed:
            ev = Event(
                etype, key[0], key[1], key[2], key[3], copy.deepcopy(obj), rv,
                copy.deepcopy(old) if old is not None else None,
            )
            if tc is not None:
                # the committing write's trace context rides the shared
                # Event (one stamp for every watcher — the encode-once
                # discipline applied to causality); out-of-band like
                # _enc_line, never on the wire
                object.__setattr__(ev, "_tc", tc)
            self._history.append(ev)
            self._note_history(ev)
            # snapshot: an injected watch drop closes (and unsubscribes)
            # the watch from inside _push, mid-iteration
            for w in list(self._watches):
                out = w._transform(ev)
                if out is not None:
                    w._push(out)
            return
        # CoW: stored snapshots are never mutated in place (every write
        # replaces the whole dict), so the event shares them — the
        # per-event double deepcopy of the legacy path is gone
        ev = Event(etype, key[0], key[1], key[2], key[3], obj, rv, old)
        if tc is not None:
            object.__setattr__(ev, "_tc", tc)
        self._history.append(ev)
        self._note_history(ev)
        self._pending.append(ev)
        if len(self._pending) >= self._emit_batch:
            self._flush_events()
        elif not self._flush_scheduled:
            if self._gc_sink():
                # group commit: this mutation's _log_wal joins (or
                # opens) a commit window, whose flush delivers the
                # fan-out once for the whole window — no per-mutation
                # scheduling (watch()/drain() still flush lazily, and
                # sync-context callers never scheduled here anyway)
                return
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return  # sync context: consumers flush lazily on access
            self._flush_scheduled = True
            loop.call_soon(self._flush_events)

    # ------------------------------------------------- batched fan-out

    def _flush_events(self) -> None:
        """Deliver pending events to all watches in one vectorized pass.

        Reentrancy-safe: an injected watch drop closes a watch from
        inside delivery, and close() itself flushes first.
        """
        self._flush_scheduled = False
        if self._flushing or not self._pending:
            return
        batch, self._pending = self._pending, []
        self._flushing = True
        t0 = time.perf_counter()
        try:
            self._fanout(batch)
        finally:
            self._flushing = False
            dt = time.perf_counter() - t0
            REGISTRY.histogram("watch_fanout_batch_size",
                               "events coalesced per watch fan-out pass",
                               buckets=SIZE_BUCKETS).observe(len(batch))
            REGISTRY.histogram("store_emit_seconds",
                               "time delivering one fan-out batch").observe(dt)
            if obs.TRACER.enabled:
                # attribute the flush to the first sampled event's trace
                # (the batch shares one delivery pass; one span suffices)
                for ev in batch:
                    tc = ev.__dict__.get("_tc")
                    if tc is not None:
                        now = time.time()
                        obs.record_span(
                            "store.fanout", obs.TRACER.child(tc),
                            tc.span_id, now - dt, dt,
                            {"events": len(batch)})
                        break

    def _fanout(self, batch: list[Event]) -> None:
        if not self._watches:
            return
        by_res: dict[str, list[Event]] = {}
        for ev in batch:
            by_res.setdefault(ev.resource, []).append(ev)
        for res, evs in by_res.items():
            if self._watches_by_res.get(res):
                self._fanout_resource(res, evs)

    def _cid(self, cluster: str) -> int:
        i = self._intern_cl.get(cluster)
        if i is None:
            i = self._intern_cl[cluster] = len(self._intern_cl)
        return i

    def _nid(self, namespace: str) -> int:
        i = self._intern_ns.get(namespace)
        if i is None:
            i = self._intern_ns[namespace] = len(self._intern_ns)
        return i

    def _fanout_plan(self, res: str):
        """The per-resource fan-out plan — the watch partition plus the
        per-watch scope/selector arrays — cached per watch-set version.
        Rebuilding this per flush was O(watches) python per mutation
        batch; at 10k live watchers the cache makes a flush O(events +
        deliveries) with the [N, C] algebra in numpy."""
        ver = self._watch_ver.get(res, 0)
        plan = self._fanout_cache.get(res)
        if plan is not None and plan[0] == ver:
            return plan
        ws = [w for w in self._watches_by_res.get(res, ()) if not w._closed]
        fb_ws = [w for w in ws if not w.selector.empty
                 and w._eq_pid is None and w._compiled is None]
        mx_ws = [w for w in ws if w.selector.empty
                 or w._eq_pid is not None or w._compiled is not None]
        w_cl = np.array([-2 if w.cluster == WILDCARD
                         else self._cid(w.cluster) for w in mx_ws], np.int32)
        w_ns = np.array([-2 if w.namespace is None
                         else self._nid(w.namespace) for w in mx_ws], np.int32)
        eq_cols = [ci for ci, w in enumerate(mx_ws) if w._eq_pid is not None]
        gen_cols = [ci for ci, w in enumerate(mx_ws) if w._compiled is not None]
        empty_cols = [ci for ci, w in enumerate(mx_ws) if w.selector.empty]
        sels = (np.array([mx_ws[ci]._eq_pid for ci in eq_cols], np.uint32)
                if eq_cols else None)
        plan = (ver, mx_ws, fb_ws, w_cl, w_ns, eq_cols, gen_cols,
                empty_cols, sels)
        self._fanout_cache[res] = plan
        return plan

    def _fanout_resource(self, res: str, evs: list[Event]) -> None:
        """One resource's events x that resource's watches, as matrices.

        Selector matching is one vectorized pass over interned label ids:
        single-equality selectors (the syncer shape) via fanout_match_np,
        kernel-shaped ones via match_batch_np, oversized ones via the
        exact per-event python path. Scope and the old-match/new-match
        ADDED/MODIFIED/DELETED rewrite of :meth:`Watch._transform` are
        then [N, C] boolean algebra; python touches only the (sparse)
        deliveries. Per-watch arrays come from the cached fan-out plan.
        """
        n = len(evs)
        (_ver, mx_ws, fb_ws, w_cl, w_ns, eq_cols, gen_cols, empty_cols,
         sels) = self._fanout_plan(res)
        if mx_ws:
            c = len(mx_ws)
            # scope[N, C]: cluster/namespace ids from the store-global
            # intern tables (stable across batches, so the w_cl/w_ns
            # arrays are cached in the plan); wildcards are -2
            cl_ids = np.fromiter((self._cid(ev.cluster) for ev in evs),
                                 np.int32, n)
            ns_ids = np.fromiter((self._nid(ev.namespace) for ev in evs),
                                 np.int32, n)
            scope = ((w_cl[None, :] == -2) | (cl_ids[:, None] == w_cl[None, :])) \
                & ((w_ns[None, :] == -2) | (ns_ids[:, None] == w_ns[None, :]))

            is_add = np.fromiter((ev.type == ADDED for ev in evs), bool, n)
            is_del = np.fromiter((ev.type == DELETED for ev in evs), bool, n)
            is_mod = ~(is_add | is_del)

            nm = np.zeros((n, c), bool)
            om = np.zeros((n, c), bool)
            if eq_cols or gen_cols:
                from ..ops import labelmatch as lm

                pair_new, key_new = self._encode_labels(evs, old=False)
                pair_old, key_old = self._encode_labels(evs, old=True)
                if eq_cols:
                    nm[:, eq_cols] = lm.fanout_match_np(pair_new, sels)
                    om[:, eq_cols] = lm.fanout_match_np(pair_old, sels)
                for ci in gen_cols:
                    cs = mx_ws[ci]._compiled
                    nm[:, ci] = lm.match_batch_np(pair_new, key_new, cs)
                    om[:, ci] = lm.match_batch_np(pair_old, key_old, cs)
            if empty_cols:
                nm[:, empty_cols] = om[:, empty_cols] = True
            nm &= ~is_del[:, None]  # _transform: new_match is False on DELETED

            as_is = scope & ((is_add[:, None] & nm)
                             | (is_del[:, None] & (om | nm))
                             | (is_mod[:, None] & nm & om))
            to_add = scope & is_mod[:, None] & nm & ~om
            to_del = scope & is_mod[:, None] & ~nm & om
            # argwhere is row-major: per-watch delivery stays in rv order.
            # Rewritten (label-transition) events are built once per
            # source event and shared across every matched watch, so the
            # encode-once wire cache on the Event pays off for them too.
            rw_add: dict[int, Event] = {}
            rw_del: dict[int, Event] = {}
            for ni, ci in np.argwhere(as_is | to_add | to_del):
                w = mx_ws[ci]
                if w._closed:
                    continue
                ev = evs[ni]
                if as_is[ni, ci]:
                    w._push(ev)
                elif to_add[ni, ci]:
                    out = rw_add.get(ni)
                    if out is None:
                        out = rw_add[ni] = Event(
                            ADDED, ev.resource, ev.cluster, ev.namespace,
                            ev.name, ev.object, ev.rv, ev.old_object)
                    w._push(out)
                else:
                    out = rw_del.get(ni)
                    if out is None:
                        out = rw_del[ni] = Event(
                            DELETED, ev.resource, ev.cluster, ev.namespace,
                            ev.name, ev.object, ev.rv, ev.old_object)
                    w._push(out)
        for w in fb_ws:
            # oversized selector: exact per-event fallback
            for ev in evs:
                if w._closed:
                    break
                out = w._transform(ev)
                if out is not None:
                    w._push(out)

    def _encode_labels(self, evs: list[Event], old: bool) -> tuple[np.ndarray, np.ndarray]:
        """Interned (pair ids, key ids), 0-padded to the batch's widest
        label set — the host-twin encoding of ops/encode.encode_label_batch."""
        labels_list = []
        width = 1
        for ev in evs:
            obj = ev.old_object if old else ev.object
            labels = ((obj or {}).get("metadata") or {}).get("labels") or {}
            labels_list.append(labels)
            width = max(width, len(labels))
        pair = np.zeros((len(evs), width), np.uint32)
        keyh = np.zeros((len(evs), width), np.uint32)
        for i, labels in enumerate(labels_list):
            for j, (k, v) in enumerate(labels.items()):
                pair[i, j] = self._pid(k, v)
                keyh[i, j] = self._kid(k)
        return pair, keyh

    @staticmethod
    def _pair_token(k: str, v: Any):
        """Intern-table key for a label pair. Strings (the k8s case) key
        directly; non-string values get a type tag so e.g. 5 and "5"
        (unequal to the python matcher) can never intern to one id, and
        unhashable values fall back to their canonical JSON."""
        if isinstance(v, str):
            return (k, v)
        try:
            hash(v)
        except TypeError:
            return (k, "\x00json", json.dumps(v, sort_keys=True, default=str))
        return (k, "\x00" + type(v).__name__, v)

    def _pid(self, k: str, v: Any) -> int:
        tok = self._pair_token(k, v)
        i = self._intern_pairs.get(tok)
        if i is None:
            i = self._intern_pairs[tok] = len(self._intern_pairs) + 1
        return i

    def _kid(self, k: str) -> int:
        i = self._intern_keys.get(k)
        if i is None:
            i = self._intern_keys[k] = len(self._intern_keys) + 1
        return i

    def _subscribe_selector(self, w: Watch) -> None:
        """Compile a watch's selector for the vectorized fan-out."""
        eq = w.selector.single_equality
        if eq is not None:
            w._eq_pid = self._pid(*eq)
            return
        if self._labelmatch is None:
            from ..ops import labelmatch

            self._labelmatch = labelmatch
        # oversized selectors return None => exact per-event fallback
        # (counted in labelmatch_fallback_total)
        w._compiled = self._labelmatch.try_compile_selector(
            w.selector, pair_hash=self._pid, key_hash=self._kid)

    def _unsubscribe(self, w: Watch) -> None:
        try:
            self._watches.remove(w)
        except ValueError:
            pass
        ws = self._watches_by_res.get(w.resource)
        if ws is not None:
            try:
                ws.remove(w)
            except ValueError:
                return  # never registered (closed during resume replay)
            if not ws:
                del self._watches_by_res[w.resource]
            self._watch_ver[w.resource] = \
                self._watch_ver.get(w.resource, 0) + 1

    # ---------------------------------------------------------- durability

    def set_repl_hook(self, hook, batch=None) -> None:
        """Install the per-commit replication callback ``hook(rec)``
        (rec is the WAL record dict: op/key/rv and obj for puts). Fires
        for every committed mutation regardless of durability backend —
        the ReplicationHub ships exactly what the WAL records. ``batch``
        (``batch(recs)``) is the group-commit form: a flushed window
        ships once through it instead of once per record."""
        self._repl_hook = hook
        self._repl_batch = batch

    # ------------------------------------------------------- group commit

    def commit_durable(self, rv: int | None = None):
        """Awaitable durability barrier for the write-serving path: the
        open commit window's future, or None when every committed
        mutation is already synced (group commit off, sync-context
        writes, or the window already flushed — a failed flush raised at
        its triggering writer). The future resolves with the window's
        HIGH RV after the shared WAL append + sync, so every writer of a
        window can park its semi-sync standby wait on the same RV (one
        ack releases the whole window); a failed sync resolves it with
        the typed error instead — fail every writer, commit none.

        Callers reach this in the same event-loop step as their mutation
        (the store is loop-owned), so the open window is always the one
        their record joined.

        Idle fast path: when the loop has no other ready work, nothing
        can join this window before its scheduled flush — flush
        synchronously NOW and skip the loop round trip, so a lone writer
        pays exactly the serial path's latency (the linger-must-not-tax-
        the-idle-case guarantee). Busy loops keep the deferred flush and
        the batching it buys."""
        w = self._gc_window
        if w is None or not w.recs:
            return None
        if w.handle is None:  # call_soon mode (no timed linger)
            try:
                ready = len(asyncio.get_running_loop()._ready)
            except (RuntimeError, AttributeError):
                ready = 2  # non-CPython loop: keep the deferred flush
            if ready <= 1:
                # the only pending callback is this window's own flush
                self._gc_flush(w)
                if w.fut.cancelled() or w.fut.exception() is not None:
                    return w.fut  # the awaiter surfaces the typed failure
                return None  # already durable: no wait needed
        return w.fut

    def _gc_sink(self) -> bool:
        """True when mutations commit through group-commit windows (the
        feature is on and there is a sink — WAL or replication hook —
        to batch for)."""
        return self._gc_enabled and (
            self._engine is not None or self._wal is not None
            or self._repl_hook is not None)

    def _gc_open(self, loop) -> _CommitWindow:
        w = _CommitWindow(loop.create_future())
        # reconcilers and other in-process writers never await the
        # window: retrieve the exception eagerly so a failed sync with no
        # HTTP writer parked on it cannot log "never retrieved"
        w.fut.add_done_callback(lambda f: f.cancelled() or f.exception())
        self._gc_window = w
        if self._gc_linger_s > 0:
            w.handle = loop.call_later(self._gc_linger_s,
                                       self._gc_flush, w)
        else:
            # no timed linger: the window closes at the next loop pass —
            # everything already runnable this pass joins it, and a lone
            # writer pays one loop iteration, not a timer tick
            loop.call_soon(self._gc_flush, w)
        return w

    def _gc_barrier(self) -> None:
        """Flush any open commit window NOW — out-of-band WAL records
        (epoch stamps, snapshot compaction, close) must not overtake
        buffered mutations in the log."""
        w = self._gc_window
        if w is not None:
            self._gc_flush(w)

    def _gc_flush(self, w: _CommitWindow) -> None:
        """Close one commit window: ONE buffered WAL append + ONE sync
        for every record in it, then ship the replication batch, resolve
        the writers, and deliver the coalesced watch fan-out. A sync
        failure fails every writer with a typed 503 and commits NONE of
        the window's records (the serial path's failure contract, window
        wide)."""
        if w.flushed:
            return  # a size-bound split already flushed it under the timer
        w.flushed = True
        if self._gc_window is w:
            self._gc_window = None
        if w.handle is not None:
            w.handle.cancel()
        recs = w.recs
        if not recs:
            if not w.fut.done():
                w.fut.set_result(0)
            return
        try:
            _inject("store.commit_window")
            if self._engine is not None:
                self._append_engine_batch(recs)
            elif self._wal is not None and self._wal.fh is not None:
                t0 = time.perf_counter()
                self._wal.fh.write("".join(
                    json.dumps(rec, separators=(",", ":")) + "\n"
                    for rec in recs))
                self._wal_fh_sync(t0)
                self._wal.mutations_since_snapshot += len(recs)
        except BaseException as e:  # noqa: BLE001 — becomes every writer's 5xx
            err = e if isinstance(e, UnavailableError) else UnavailableError(
                f"commit window sync failed ({len(recs)} writes "
                f"uncommitted): {e}")
            err.__cause__ = None if e is err else e
            log.error("commit window FAILED: %s", err.message)
            if not w.fut.done():
                w.fut.set_exception(err)
            # deliver what was emitted (in-memory state advanced exactly
            # as a serial post-emit failure leaves it); nothing ships
            self._flush_events()
            return
        self._gc_windows_total.inc()
        self._gc_window_size.observe(len(recs))
        # replication ships AFTER the local sync: a window that dies
        # pre-sync was never acked anywhere — one batch, one queue push
        # per subscriber
        if self._repl_batch is not None:
            self._repl_batch(recs)
        elif self._repl_hook is not None:
            for rec in recs:
                self._repl_hook(rec)
        if not w.fut.done():
            w.fut.set_result(w.high_rv)
        # one fan-out flush per window (not per mutation)
        self._flush_events()
        if self._engine is not None:
            if self._engine_mutations >= self._engine_snapshot_every:
                self.snapshot()
        elif (self._wal is not None and self._wal.fh is not None
                and self._wal.mutations_since_snapshot
                >= self._wal.snapshot_every):
            self.snapshot()

    def _wal_fh_sync(self, t0: float) -> None:
        """Apply the KCP_WAL_SYNC policy to the JSON-lines WAL after an
        append (metered): ``flush`` pushes python's buffer to the OS,
        ``fsync`` additionally forces the platters, ``off`` leaves both
        to chance."""
        if self._wal_sync == "off":
            return
        fh = self._wal.fh
        fh.flush()
        if self._wal_sync == "fsync":
            os.fsync(fh.fileno())
        self._wal_sync_total.inc()
        self._wal_sync_seconds.observe(time.perf_counter() - t0)

    def _append_engine_batch(self, recs: list[dict]) -> None:
        """One native multi-record append (ws_batch_begin/commit): the
        whole window's records buffer into one write() and at most one
        fsync, per the KCP_WAL_SYNC policy."""
        t0 = time.perf_counter()
        ops = []
        for rec in recs:
            key = _wal_key(tuple(rec["key"]))
            if rec["op"] == "put":
                ops.append((key, json.dumps(
                    rec["obj"], separators=(",", ":")).encode("utf-8"),
                    rec["rv"]))
            else:
                ops.append((key, None, rec["rv"]))
        self._engine.append_batch(ops, fsync=self._wal_sync == "fsync")
        if self._wal_sync != "off":
            self._wal_sync_total.inc()
            self._wal_sync_seconds.observe(time.perf_counter() - t0)
        self._engine_mutations += len(recs)

    def _log_wal(self, rec: dict) -> None:
        if self._gc_sink():
            # group commit: join (or open) the commit window — the
            # record's durable append, replication ship, and fan-out
            # flush all happen at the window flush. Only under a running
            # loop: sync-context callers have nothing to drive the flush.
            w = self._gc_window
            if w is None:
                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None
                if loop is not None:
                    w = self._gc_open(loop)
            if w is not None:
                w.recs.append(rec)
                rv = int(rec.get("rv", 0) or 0)
                if rv > w.high_rv:
                    w.high_rv = rv
                if (len(w.recs) >= self._gc_max
                        or should_drop("store.commit_window")):
                    # row bound reached (or an injected split drill):
                    # flush now — the failure, if any, surfaces on the
                    # shared future, which this writer is about to await
                    self._gc_flush(w)
                return
        # serial path: group commit off, or no loop to drive a window
        # (replication rides the WAL record stream: the hook sees every
        # committed record — in-memory stores included)
        if self._repl_hook is not None:
            self._repl_hook(rec)
        if self._engine is not None:
            key = _wal_key(tuple(rec["key"]))
            t0 = time.perf_counter()
            if rec["op"] == "put":
                self._engine.put(
                    key,
                    json.dumps(rec["obj"], separators=(",", ":")).encode("utf-8"),
                    rec["rv"],
                )
            else:
                self._engine.delete(key, rec["rv"])
            if self._wal_sync == "fsync":
                # per-record durability: the serial A/B reference whose
                # cost the commit window exists to amortize
                self._engine.flush()
                self._wal_sync_total.inc()
                self._wal_sync_seconds.observe(time.perf_counter() - t0)
            self._engine_mutations += 1
            if self._engine_mutations >= self._engine_snapshot_every:
                self.snapshot()
            return
        if self._wal is None or self._wal.fh is None:
            return
        t0 = time.perf_counter()
        self._wal.fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal_fh_sync(t0)
        self._wal.mutations_since_snapshot += 1
        if self._wal.mutations_since_snapshot >= self._wal.snapshot_every:
            self.snapshot()

    def _load_engine(self) -> None:
        assert self._engine is not None
        for key, val in self._engine.scan():
            parts = tuple(key.decode("utf-8").split("\x00"))
            self._put_obj(parts, json.loads(val))
        self._rv = self._engine.rv
        self.epoch = max(self.epoch, getattr(self._engine, "epoch", 0))
        # journal-only mode: this store holds the authoritative objects,
        # so the engine's duplicate value map would only double memory
        self._engine.release_index()

    # --------------------------------------------------------- replication

    def set_epoch(self, epoch: int) -> None:
        """Adopt a replication epoch (>= the current one; epochs never
        rewind) and persist it with the WAL so a restart cannot undo a
        fence or a promotion."""
        epoch = int(epoch)
        if epoch < self.epoch:
            raise InvalidError(
                f"epoch {epoch} < current {self.epoch}: epochs never rewind")
        self.epoch = epoch
        self._gc_barrier()  # the epoch record must not overtake a window
        if self._engine is not None:
            self._engine.set_epoch(epoch)
        elif self._wal is not None and self._wal.fh is not None:
            self._wal.fh.write(
                json.dumps({"op": "epoch", "epoch": epoch},
                           separators=(",", ":")) + "\n")
            self._wal.fh.flush()

    def fence(self, epoch: int) -> None:
        """A newer epoch superseded this store (a standby promoted over
        it): adopt the epoch and refuse all further writes. The zombie-
        primary kill switch — after this, the old primary can neither
        commit client writes nor ship records anywhere."""
        self.set_epoch(epoch)
        self.fenced = True
        self.read_only = f"fenced: epoch {epoch} superseded this primary"
        log.warning("store fenced at epoch %d: refusing writes", epoch)

    def apply_replicated(self, rec: dict, epoch: int | None = None) -> bool:
        """Apply one shipped WAL record exactly as the primary committed
        it: the record's RV becomes this store's RV (no local allocation,
        no admission, no validation — the primary already did all that),
        watch events fan out so replica informers stay live, and the
        record lands in the local WAL for replica durability.

        Records carrying an epoch older than this store's are rejected
        with a typed 410 (fencing: a zombie primary's late records must
        not land after a promotion). Records at or below the applied RV
        are no-ops (reconnect overlap), returning False.
        """
        self._race_guard.check()
        if epoch is not None and epoch < self.epoch:
            REGISTRY.counter(
                "repl_fenced_writes_total",
                "writes refused because this store was fenced by a "
                "newer replication epoch").inc()
            raise GoneError(
                f"replication record from epoch {epoch} rejected: this "
                f"store is at epoch {self.epoch}")
        op = rec.get("op")
        if op == "epoch":
            e = int(rec["epoch"])
            if e > self.epoch:
                self.set_epoch(e)
            return True
        rv = int(rec["rv"])
        if rv <= self._rv:
            return False
        key: Key = tuple(rec["key"])  # type: ignore[assignment]
        # the primary's sampled-write trace context rides the shipped
        # record: replica-side events carry the same causality, and the
        # re-logged record keeps it for chained followers
        tctx = obs.ctx_from_wal(rec.get("tc"))
        if op == "put":
            old = self._objects.get(key)
            # ownership transfer: the record dict was parsed off the
            # feed and is not shared — stored as the snapshot directly
            obj = self._put_obj(key, rec["obj"])
            self._rv = rv
            self._emit(MODIFIED if old is not None else ADDED,
                       key, obj, rv, old=old, tc=tctx)
            out_rec = {"op": "put", "key": list(key), "obj": obj,
                       "rv": rv}
            if tctx is not None:
                out_rec["tc"] = rec["tc"]
                obs.link_obj(obj, tctx)
            self._log_wal(out_rec)
        elif op == "del":
            existing = self._objects.get(key)
            self._del_obj(key)
            self._rv = rv
            if rec.get("mig"):
                # a migration purge on the primary: the object MOVED to
                # another shard, it was not deleted — no DELETED event
                # (a phantom delete would evict live informer caches);
                # cluster-scoped watchers on this replica are evicted to
                # a typed 410 so they relist against the new owner.
                for w in list(self._watches):
                    if w.cluster == key[1]:
                        w._evict()
            elif existing is not None:
                self._emit(DELETED, key, existing, rv, old=existing,
                           tc=tctx)
            out_rec = {"op": "del", "key": list(key), "rv": rv}
            if rec.get("mig"):
                out_rec["mig"] = 1
            if tctx is not None:
                out_rec["tc"] = rec["tc"]
            self._log_wal(out_rec)
        else:
            raise InvalidError(f"unknown replication record op {op!r}")
        return True

    # ----------------------------------------------------------- migration
    #
    # Live per-cluster migration (sharding/migrate.py): the source shard
    # fences one cluster at a cutover RV, streams its objects to the new
    # owner, the ring flips that one cluster, then the source purges it.
    # Source and target mint RVs independently, so migrated objects get
    # FRESH local RVs on the target and the source's RV history for the
    # cluster becomes unreachable — the floor bookkeeping makes stale
    # resumes answer a typed 410 instead of a silent partial resume.

    def fence_cluster(self, cluster: str) -> int:
        """Refuse further writes to one logical cluster and return the
        cutover RV: every write this store ever acked for the cluster
        has rv <= the returned value (the group-commit barrier flushes
        in-flight windows first, so the replication window and the WAL
        both already hold them). Idempotent."""
        self._race_guard.check()
        cut = self._cluster_fences.get(cluster)
        if cut is not None:
            return cut
        self._gc_barrier()
        self._flush_events()
        self._cluster_fences[cluster] = self._rv
        log.info("cluster %s fenced for migration at rv %d", cluster,
                 self._rv)
        return self._rv

    def unfence_cluster(self, cluster: str) -> None:
        """Roll back a cluster fence (an aborted migration)."""
        self._race_guard.check()
        self._cluster_fences.pop(cluster, None)

    def apply_migrated(self, rec: dict) -> int | None:
        """Apply one migrated record from a cluster moving ONTO this
        shard. Unlike :meth:`apply_replicated`, the source's RVs mean
        nothing here (independent counters): the object gets a fresh
        local RV and only ``metadata.resourceVersion`` is re-stamped —
        uid, creationTimestamp and every other byte survive the move.
        Watch events fan out (ADDED for the common post-fence snapshot
        case) so wildcard informers converge without a relist, and the
        record lands in the local WAL. Returns the local rv, or None
        for a no-op."""
        self._race_guard.check()
        self._check_writable()
        op = rec.get("op")
        if op == "epoch":
            return None
        key: Key = tuple(rec["key"])  # type: ignore[assignment]
        REGISTRY.counter(
            "migration_records_total",
            "migrated WAL records applied on a cluster's new owning "
            "shard").inc()
        if op == "put":
            obj = copy.deepcopy(rec["obj"])
            old = self._objects.get(key)
            rv = self._next_rv()
            obj.setdefault("metadata", {})["resourceVersion"] = str(rv)
            obj = self._put_obj(key, obj)
            self._emit(MODIFIED if old is not None else ADDED, key, obj,
                       rv, old=old)
            self._log_wal({"op": "put", "key": list(key), "obj": obj,
                           "rv": rv})
            return rv
        if op == "del":
            existing = self._objects.get(key)
            if existing is None:
                return None
            rv = self._next_rv()
            self._del_obj(key)
            self._emit(DELETED, key, existing, rv, old=existing)
            self._log_wal({"op": "del", "key": list(key), "rv": rv})
            return rv
        raise InvalidError(f"unknown migration record op {op!r}")

    def advance_rv(self, min_rv: int) -> None:
        """Jump the RV counter to at least ``min_rv`` (never rewinds).
        Used at migration finish so every RV this shard mints afterwards
        sorts AFTER every RV the source ever minted for the cluster."""
        self._race_guard.check()
        min_rv = int(min_rv)
        if min_rv > self._rv:
            self._rv = min_rv
            if self._engine is not None:
                self._engine.set_rv(self._rv)

    def finish_migration(self, cluster: str, source_rv: int) -> int:
        """Target-side cutover bookkeeping: advance past everything the
        source ever minted and record the cluster's RV floor — resumes
        below it carry source-minted RVs and answer a typed 410 (see
        :meth:`watch`). Returns the floor."""
        self._race_guard.check()
        self.advance_rv(int(source_rv) + 1)
        self._migration_floors[cluster] = self._rv
        return self._rv

    def purge_cluster(self, cluster: str) -> int:
        """Source-side teardown after the cluster's ownership flipped:
        deliver everything already emitted, end the cluster's watch
        streams through the eviction path (terminal typed 410 after
        their buffers drain — nothing committed pre-cutover is lost),
        then drop the cluster's objects WITHOUT watch events: the move
        is not a delete, observers re-attach to the new owner. The WAL
        del records (tagged ``mig``) keep restarts and WAL-fed replicas
        consistent and wildcard scatter-lists duplicate-free. Returns
        the number of objects purged."""
        self._race_guard.check()
        self._gc_barrier()
        self._flush_events()
        for w in list(self._watches):
            if w.cluster == cluster:
                w._evict()
        keys = [k for k in self._objects if k[1] == cluster]
        for key in keys:
            rv = self._next_rv()
            self._del_obj(key)
            self._log_wal({"op": "del", "key": list(key), "rv": rv,
                           "mig": 1})
        self._cluster_fences.pop(cluster, None)
        log.info("cluster %s purged after migration: %d objects", cluster,
                 len(keys))
        return len(keys)

    def reset_for_resync(self) -> None:
        """Drop all local state ahead of a full snapshot resync (the
        primary's retained ship window no longer covers our applied RV).
        Open watches close — their consumers re-list, exactly as after a
        410 — and the caller streams snapshot objects in via
        :meth:`load_snapshot_object` + :meth:`finish_resync`."""
        self._gc_barrier()
        self._flush_events()
        for w in list(self._watches):
            w.close()
        self._objects.clear()
        self._buckets.clear()
        self._history.clear()
        self._hist_events.clear()
        self._hist_rvs.clear()
        self._hist_start = 0
        self._pending.clear()
        self._enc_bytes.clear()
        self._span_cache.clear()
        self._bucket_ver.clear()
        self._rv = 0

    def load_snapshot_object(self, key, obj: dict) -> None:
        """Insert one snapshot object during a resync (no events, no RV
        bookkeeping — :meth:`finish_resync` sets the RV watermark)."""
        self._put_obj(tuple(key), obj)

    def finish_resync(self, rv: int) -> None:
        """Stamp the snapshot's RV watermark and compact local
        durability so a replica restart resumes from this point."""
        self._rv = max(self._rv, int(rv))
        if self._engine is not None:
            self._engine.set_rv(self._rv)
        if self._engine is not None or self._wal is not None:
            self.snapshot()

    def _apply_wal_record(self, rec: dict) -> None:
        """Replay one JSON WAL record into the in-memory state."""
        op = rec.get("op")
        if op == "epoch":
            self.epoch = max(self.epoch, int(rec["epoch"]))
            return
        key = tuple(rec["key"])
        if op == "put":
            self._put_obj(key, rec["obj"])
        elif op == "del":
            self._del_obj(key)
        else:
            raise ValueError(f"unknown WAL op {op!r}")
        self._rv = max(self._rv, int(rec.get("rv", 0)))

    def _load_wal(self) -> None:
        assert self._wal is not None
        snap = self._wal.path + ".snap"
        if os.path.exists(snap):
            with open(snap, encoding="utf-8") as f:
                data = json.load(f)
            self._rv = data["rv"]
            self.epoch = max(self.epoch, int(data.get("epoch", 0)))
            for rec in data["objects"]:
                self._put_obj(tuple(rec["key"]), rec["obj"])
        if not os.path.exists(self._wal.path):
            return
        with open(self._wal.path, "rb") as f:
            raw = f.read()
        # torn-tail recovery (the JSON twin of the native engine's CRC
        # replay): a crash mid-append leaves a partial (or garbled) final
        # record — replay stops at the first record that fails to parse
        # and the file is truncated to the last good one, instead of
        # failing the whole restore and wedging the server on boot.
        pos = 0
        end_good = 0
        while pos < len(raw):
            nl = raw.find(b"\n", pos)
            terminated = nl >= 0
            chunk = raw[pos:nl] if terminated else raw[pos:]
            nxt = nl + 1 if terminated else len(raw)
            if chunk.strip():
                try:
                    self._apply_wal_record(json.loads(chunk))
                except (ValueError, KeyError, TypeError) as e:
                    log.warning(
                        "WAL %s: torn/corrupt record at byte %d (%s); "
                        "truncating to last good record (%d bytes dropped)",
                        self._wal.path, pos, e, len(raw) - end_good)
                    REGISTRY.counter(
                        "wal_torn_tail_total",
                        "WAL restores that dropped a torn/corrupt tail"
                    ).inc()
                    os.truncate(self._wal.path, end_good)
                    return
            end_good = nxt
            pos = nxt

    def snapshot(self) -> None:
        """Write a snapshot and truncate the WAL (etcd compaction analog)."""
        self._gc_barrier()  # compaction must not strand buffered records
        if self._engine is not None:
            self._engine.snapshot_stream(
                (_wal_key(k), json.dumps(v, separators=(",", ":")).encode("utf-8"))
                for k, v in self._objects.items()
            )
            self._engine_mutations = 0
            return
        if self._wal is None:
            return
        snap = self._wal.path + ".snap"
        tmp = snap + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "rv": self._rv,
                    "epoch": self.epoch,
                    "objects": [
                        {"key": list(k), "obj": v} for k, v in self._objects.items()
                    ],
                },
                f,
            )
        os.replace(tmp, snap)
        if self._wal.fh is not None:
            self._wal.fh.close()
        self._wal.fh = open(self._wal.path, "w", encoding="utf-8")
        self._wal.mutations_since_snapshot = 0

    def close(self) -> None:
        self._gc_barrier()  # an open window's records reach the WAL first
        self._flush_events()
        for w in list(self._watches):
            w.close()
        if self._engine is not None:
            self._engine.close()
            self._engine = None
        if self._wal is not None and self._wal.fh is not None:
            self._wal.fh.close()
            self._wal.fh = None

    # ----------------------------------------------------------- internal

    @staticmethod
    def _non_status_changed(a: Mapping, b: Mapping) -> bool:
        """True when anything outside .status and volatile metadata differs.

        The host-side twin of the device diff kernel's spec lane
        (reference behavior: pkg/syncer/specsyncer.go:17-41
        deepEqualApartFromStatus ignores status + mutable metadata).
        """

        def strip(o: Mapping) -> dict:
            o = {k: v for k, v in o.items() if k != "status"}
            meta = dict(o.get("metadata") or {})
            for f in ("resourceVersion", "generation", "managedFields", "creationTimestamp", "uid"):
                meta.pop(f, None)
            o["metadata"] = meta
            return o

        return strip(a) != strip(b)


def iter_keys(store: LogicalStore) -> Iterator[Key]:
    return iter(store._objects.keys())
