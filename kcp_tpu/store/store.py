"""LogicalStore: the multi-tenant keyspace + watch hub.

This is the storage layer of the framework — the analog of the reference's
embedded etcd plus the forked apiserver's logical-cluster storage prefixing
(reference: pkg/etcd/etcd.go; docs/investigations/logical-clusters.md:66-74,
key scheme ``/<resource>/<cluster>/<namespace>/<name>``). It is deliberately
also the test fake: the same object backs unit tests, the in-process API
server, and the fake physical clusters.

Semantics implemented (inferred from the reference's call sites, since the
kcp-dev/kubernetes fork is not vendored there):

- logical-cluster prefix keys; ``*`` (WILDCARD) lists/watches across all
  tenants (logical-clusters.md:70-74)
- a single monotonically increasing resourceVersion per store (etcd
  revision analog); lists carry the store RV, watches can resume from an RV
- optimistic concurrency: update with a stale metadata.resourceVersion
  raises ConflictError
- generation bumps on spec (non-status) changes only; status subresource
  updates never bump generation
- finalizers: delete sets deletionTimestamp first; object is removed when
  the finalizer list is empty
- label-selector filtered list/watch
- optional durability via an append-only JSON-lines WAL with snapshot
  compaction (restart resumes from durable storage, matching the
  reference's restart-resumes-from-etcd model, server.go:80-97)

Thread-model: single-threaded synchronous core intended to be called from
one asyncio event loop; watches buffer into deques and optionally notify an
asyncio.Event so async consumers can await new events.
"""

from __future__ import annotations

import asyncio
import copy
import json
import os
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Mapping

from ..faults import maybe_fail, should_drop
from ..utils.errors import (
    AlreadyExistsError,
    ConflictError,
    InvalidError,
    NotFoundError,
)
from .selectors import LabelSelector, everything

WILDCARD = "*"

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

Key = tuple[str, str, str, str]  # (resource, cluster, namespace, name)


@dataclass(frozen=True)
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    resource: str
    cluster: str
    namespace: str
    name: str
    object: dict
    rv: int
    old_object: dict | None = None  # prior state on MODIFIED/DELETED

    @property
    def key(self) -> Key:
        return (self.resource, self.cluster, self.namespace, self.name)


class Watch:
    """A filtered subscription to store events.

    Sync consumers call :meth:`drain`; async consumers iterate with
    ``async for``. Closing is idempotent.
    """

    def __init__(
        self,
        store: "LogicalStore",
        resource: str,
        cluster: str,
        namespace: str | None,
        selector: LabelSelector,
    ):
        self._store = store
        self.resource = resource
        self.cluster = cluster
        self.namespace = namespace
        self.selector = selector
        self._events: deque[Event] = deque()
        self._closed = False
        self._wakeup: asyncio.Event | None = None

    def _scope_match(self, ev: Event) -> bool:
        if ev.resource != self.resource:
            return False
        if self.cluster != WILDCARD and ev.cluster != self.cluster:
            return False
        return self.namespace is None or ev.namespace == self.namespace

    @staticmethod
    def _labels(obj: dict | None) -> dict:
        return ((obj or {}).get("metadata") or {}).get("labels") or {}

    def _transform(self, ev: Event) -> Event | None:
        """Filter/rewrite an event for this watch's selector.

        Kubernetes apiserver semantics for selector-bound watches: an
        object whose labels *stop* matching surfaces as DELETED (so caches
        evict it), one whose labels *start* matching on an update surfaces
        as ADDED. Without this, selector-bound informer caches go
        permanently stale on label transitions.
        """
        if not self._scope_match(ev):
            return None
        if self.selector.empty:
            return ev
        new_match = ev.type != DELETED and self.selector.matches(self._labels(ev.object))
        old_match = self.selector.matches(self._labels(ev.old_object))
        if ev.type == ADDED:
            return ev if new_match else None
        if ev.type == DELETED:
            return ev if old_match or new_match else None
        if new_match and old_match:
            return ev
        if new_match:
            return Event(ADDED, ev.resource, ev.cluster, ev.namespace, ev.name,
                         ev.object, ev.rv, ev.old_object)
        if old_match:
            return Event(DELETED, ev.resource, ev.cluster, ev.namespace, ev.name,
                         ev.object, ev.rv, ev.old_object)
        return None

    def _push(self, ev: Event) -> None:
        if self._closed:
            return
        if should_drop("watch"):
            # injected stream loss (KCP_FAULTS `watch:drop...`): the event
            # is lost and the watch dies mid-stream, exactly like a
            # dropped connection — consumers must re-list (informers do)
            self.close()
            return
        self._events.append(ev)
        if self._wakeup is not None:
            self._wakeup.set()

    def drain(self) -> list[Event]:
        """Return and clear all buffered events (sync consumers/tests)."""
        out = list(self._events)
        self._events.clear()
        if self._wakeup is not None:
            self._wakeup.clear()
        return out

    def pending(self) -> int:
        return len(self._events)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._store._unsubscribe(self)
            if self._wakeup is not None:
                self._wakeup.set()

    @property
    def closed(self) -> bool:
        return self._closed

    def __aiter__(self) -> "Watch":
        return self

    async def __anext__(self) -> Event:
        while True:
            if self._events:
                return self._events.popleft()
            if self._closed:
                raise StopAsyncIteration
            if self._wakeup is None:
                self._wakeup = asyncio.Event()
            self._wakeup.clear()
            await self._wakeup.wait()

    async def next_batch(self, max_wait: float = 0.05) -> list[Event]:
        """Await at least one event (or closure), then drain the buffer.

        The batching primitive for the TPU backend: the reconcile tick
        collects a delta batch instead of handling events one at a time.
        """
        if not self._events and not self._closed:
            if self._wakeup is None:
                self._wakeup = asyncio.Event()
            self._wakeup.clear()
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout=max_wait)
            except asyncio.TimeoutError:
                pass
        return self.drain()


@dataclass
class _WalConfig:
    path: str
    fh: Any = None
    mutations_since_snapshot: int = 0
    snapshot_every: int = 50_000


def _wal_key(key: Key) -> bytes:
    """NUL-joined key tuple: ordered by (resource, cluster, ns, name) so
    native prefix scans follow the etcd range-scan idiom."""
    return "\x00".join(key).encode("utf-8")


_WAL_MAGIC = b"KCPWAL1\n"  # stamped by native/walstore.cc on every file


def _inject(point: str) -> None:
    """KCP_FAULTS injection for a store verb: may raise an injected 503
    (UnavailableError) or sleep an injected latency. Near-free when no
    injector is active."""
    delay = maybe_fail(point)
    if delay:
        time.sleep(delay)


def _detect_wal_format(path: str) -> str | None:
    """Detect an existing WAL's format: "json" (JSON-lines), "native"
    (binary, identified by its magic header), or None (absent/empty).

    The magic header is authoritative — a binary record length whose low
    byte happens to be 0x7B ('{') must never read as JSON. JSON-lines
    files (which always start with ``{"op":`` or a ``{`` snapshot) are
    recognized explicitly; any other nonempty content is treated as
    native so the engine's CRC replay (which tolerates legacy
    magic-less files) gets to decide.
    """
    for candidate in (path, path + ".snap"):
        try:
            with open(candidate, "rb") as f:
                head = f.read(len(_WAL_MAGIC))
        except OSError:
            continue
        if not head:
            continue
        if head == _WAL_MAGIC:
            return "native"
        return "json" if head.lstrip()[:1] == b"{" else "native"
    return None


class LogicalStore:
    """The multi-tenant object store + watch hub."""

    def __init__(
        self,
        wal_path: str | None = None,
        clock: Callable[[], float] = time.time,
        wal_backend: str = "auto",
        wal_sync_every: int = 256,
        namespace_lifecycle: bool = False,
    ):
        """``wal_backend``: "auto" uses the native C++ engine
        (native/walstore.cc — binary records, CRC32 torn-write recovery,
        batched fsync) when the library loads, else the JSON-lines
        fallback; "native"/"json" force a choice.

        ``namespace_lifecycle``: stamp the ``kubernetes`` finalizer on
        namespaces at create (admission-style). Only enable where a
        NamespaceLifecycleController will actually release it — the kcp
        server does; bare stores and physical-cluster fakes must not,
        or their namespaces can never finish deleting.
        """
        self.namespace_lifecycle = namespace_lifecycle
        # Attachable /openapi/v2 (swagger) document for this store's
        # API surface — the discovery metadata the CRD puller's schema
        # synthesis consumes (reference: kube-openapi models fed into
        # SchemaConverter, pkg/crdpuller/discovery.go:190-207). Not
        # persisted: it is serving metadata, not state.
        self.openapi_doc: dict | None = None
        # race detection (KCP_RACE=1, the `go test -race` analog): the
        # store is loop-owned single-threaded state — every mutation
        # asserts it runs on the owning thread (utils/raceguard.py)
        from ..utils.raceguard import AffinityGuard

        self._race_guard = AffinityGuard("LogicalStore")
        self._objects: dict[Key, dict] = {}
        self._rv = 0
        self._watches: list[Watch] = []
        self._history: deque[Event] = deque(maxlen=200_000)
        self._clock = clock
        self._wal: _WalConfig | None = None
        self._engine = None
        self._engine_mutations = 0
        self._engine_snapshot_every = 50_000
        if wal_backend not in ("auto", "native", "json"):
            raise InvalidError(f"unknown wal_backend {wal_backend!r} (auto|native|json)")
        if wal_path:
            existing = _detect_wal_format(wal_path)
            if wal_backend == "auto":
                # never reinterpret an existing WAL under a different
                # format — the native engine would truncate a JSON WAL as
                # a torn tail and destroy it
                use_native = existing != "json"
            elif wal_backend == "native":
                if existing == "json":
                    raise InvalidError(
                        f"{wal_path} holds a JSON-lines WAL; migrate it (load with "
                        f"wal_backend='json', snapshot to a fresh path) before "
                        f"forcing the native engine"
                    )
                use_native = True
            else:
                if existing == "native":
                    raise InvalidError(
                        f"{wal_path} holds a native binary WAL; it cannot be "
                        f"opened with wal_backend='json'"
                    )
                use_native = False
            if use_native:
                try:
                    from ..native import WalEngine

                    self._engine = WalEngine(wal_path, sync_every=wal_sync_every)
                except Exception:
                    if wal_backend == "native":
                        raise
                    if existing == "native":
                        raise  # a binary WAL is unreadable without the engine
            if self._engine is not None:
                self._load_engine()
            else:
                self._wal = _WalConfig(path=wal_path)
                self._load_wal()
                self._wal.fh = open(wal_path, "a", encoding="utf-8")

    # ------------------------------------------------------------------ RV

    @property
    def resource_version(self) -> int:
        return self._rv

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # ------------------------------------------------------------- helpers

    @staticmethod
    def _key(resource: str, cluster: str, namespace: str, name: str) -> Key:
        if not resource or not cluster or not name:
            raise InvalidError("resource, cluster and name are required")
        if cluster == WILDCARD:
            raise InvalidError("wildcard cluster is read-only")
        return (resource, cluster, namespace or "", name)

    @staticmethod
    def _meta(obj: Mapping) -> dict:
        return obj.get("metadata") or {}

    def _now(self) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self._clock()))

    # --------------------------------------------------------------- CRUD

    def create(self, resource: str, cluster: str, obj: dict, namespace: str = "") -> dict:
        self._race_guard.check()
        _inject("store.put")
        obj = copy.deepcopy(obj)
        meta = obj.setdefault("metadata", {})
        name = meta.get("name")
        if not name:
            if meta.get("generateName"):
                name = meta["generateName"] + uuid.uuid4().hex[:6]
                meta["name"] = name
            else:
                raise InvalidError("metadata.name is required")
        namespace = namespace or meta.get("namespace") or ""
        key = self._key(resource, cluster, namespace, name)
        if key in self._objects:
            raise AlreadyExistsError(f"{resource} {cluster}/{namespace}/{name} already exists")
        if resource == "namespaces" and self.namespace_lifecycle:
            # admission-style lifecycle finalizer, stamped synchronously at
            # create (as the real apiserver's NamespaceLifecycle admission
            # does) so a create+delete race can never skip the content
            # sweep in reconcilers/namespace.py
            fins = meta.setdefault("finalizers", [])
            if "kubernetes" not in fins:
                fins.append("kubernetes")
        meta["namespace"] = namespace
        meta["clusterName"] = cluster
        meta["uid"] = meta.get("uid") or str(uuid.uuid4())
        meta["creationTimestamp"] = self._now()
        meta["generation"] = 1
        rv = self._next_rv()
        meta["resourceVersion"] = str(rv)
        self._objects[key] = obj
        self._emit(ADDED, key, obj, rv)
        self._log_wal({"op": "put", "key": list(key), "obj": obj, "rv": rv})
        return copy.deepcopy(obj)

    def get(self, resource: str, cluster: str, name: str, namespace: str = "") -> dict:
        _inject("store.get")
        key = self._key(resource, cluster, namespace, name)
        obj = self._objects.get(key)
        if obj is None:
            raise NotFoundError(f"{resource} {cluster}/{namespace}/{name} not found")
        return copy.deepcopy(obj)

    def update(
        self,
        resource: str,
        cluster: str,
        obj: dict,
        namespace: str = "",
        subresource: str | None = None,
    ) -> dict:
        self._race_guard.check()
        _inject("store.put")
        obj = copy.deepcopy(obj)
        meta = self._meta(obj)
        name = meta.get("name")
        if not name:
            raise InvalidError("metadata.name is required")
        namespace = namespace or meta.get("namespace") or ""
        key = self._key(resource, cluster, namespace, name)
        existing = self._objects.get(key)
        if existing is None:
            raise NotFoundError(f"{resource} {cluster}/{namespace}/{name} not found")
        ex_meta = existing["metadata"]
        supplied_rv = meta.get("resourceVersion")
        if supplied_rv and supplied_rv != ex_meta["resourceVersion"]:
            raise ConflictError(
                f"{resource} {cluster}/{namespace}/{name}: stale resourceVersion "
                f"{supplied_rv} (current {ex_meta['resourceVersion']})"
            )
        if subresource == "status":
            new_obj = copy.deepcopy(existing)
            new_obj["status"] = obj.get("status")
        else:
            new_obj = obj
            # status is only writable through the status subresource
            if "status" in existing:
                new_obj["status"] = copy.deepcopy(existing["status"])
            elif "status" in new_obj:
                del new_obj["status"]
        new_meta = new_obj.setdefault("metadata", {})
        if subresource != "status":
            # metadata edits (labels/annotations/finalizers) ride spec updates
            preserved = {
                "uid": ex_meta.get("uid"),
                "creationTimestamp": ex_meta.get("creationTimestamp"),
                "clusterName": cluster,
                "namespace": namespace,
                "name": name,
            }
            new_meta.update(preserved)
            if ex_meta.get("deletionTimestamp"):
                new_meta["deletionTimestamp"] = ex_meta["deletionTimestamp"]
        else:
            new_obj["metadata"] = copy.deepcopy(ex_meta)
            new_meta = new_obj["metadata"]

        spec_changed = subresource != "status" and self._non_status_changed(existing, new_obj)
        new_meta["generation"] = ex_meta.get("generation", 1) + (1 if spec_changed else 0)
        rv = self._next_rv()
        new_meta["resourceVersion"] = str(rv)
        self._objects[key] = new_obj

        # finalizer-driven deletion completion
        if new_meta.get("deletionTimestamp") and not new_meta.get("finalizers"):
            del self._objects[key]
            self._emit(DELETED, key, new_obj, rv, old=existing)
            self._log_wal({"op": "del", "key": list(key), "rv": rv})
        else:
            self._emit(MODIFIED, key, new_obj, rv, old=existing)
            self._log_wal({"op": "put", "key": list(key), "obj": new_obj, "rv": rv})
        return copy.deepcopy(new_obj)

    def update_status(self, resource: str, cluster: str, obj: dict, namespace: str = "") -> dict:
        return self.update(resource, cluster, obj, namespace, subresource="status")

    def delete(self, resource: str, cluster: str, name: str, namespace: str = "") -> None:
        self._race_guard.check()
        _inject("store.delete")
        key = self._key(resource, cluster, namespace, name)
        existing = self._objects.get(key)
        if existing is None:
            raise NotFoundError(f"{resource} {cluster}/{namespace}/{name} not found")
        meta = existing["metadata"]
        if meta.get("finalizers"):
            if not meta.get("deletionTimestamp"):
                obj = copy.deepcopy(existing)
                obj["metadata"]["deletionTimestamp"] = self._now()
                rv = self._next_rv()
                obj["metadata"]["resourceVersion"] = str(rv)
                self._objects[key] = obj
                self._emit(MODIFIED, key, obj, rv, old=existing)
                self._log_wal({"op": "put", "key": list(key), "obj": obj, "rv": rv})
            return
        del self._objects[key]
        rv = self._next_rv()
        self._emit(DELETED, key, existing, rv, old=existing)
        self._log_wal({"op": "del", "key": list(key), "rv": rv})

    # --------------------------------------------------------------- list

    def list(
        self,
        resource: str,
        cluster: str = WILDCARD,
        namespace: str | None = None,
        selector: LabelSelector | None = None,
    ) -> tuple[list[dict], int]:
        """Return (items, list resourceVersion)."""
        _inject("store.list")
        selector = selector or everything()
        out = []
        for (res, cl, ns, _name), obj in self._objects.items():
            if res != resource:
                continue
            if cluster != WILDCARD and cl != cluster:
                continue
            if namespace is not None and ns != namespace:
                continue
            labels = (obj.get("metadata") or {}).get("labels") or {}
            if not selector.matches(labels):
                continue
            out.append(copy.deepcopy(obj))
        out.sort(key=lambda o: (o["metadata"].get("clusterName", ""),
                                o["metadata"].get("namespace", ""),
                                o["metadata"]["name"]))
        return out, self._rv

    def resources(self) -> list[str]:
        """Distinct resource names present in the store."""
        return sorted({k[0] for k in self._objects})

    def clusters(self) -> list[str]:
        """Distinct logical-cluster names present in the store."""
        return sorted({k[1] for k in self._objects})

    def __len__(self) -> int:
        return len(self._objects)

    # -------------------------------------------------------------- watch

    def watch(
        self,
        resource: str,
        cluster: str = WILDCARD,
        namespace: str | None = None,
        selector: LabelSelector | None = None,
        since_rv: int | None = None,
    ) -> Watch:
        """Subscribe. With ``since_rv``, replays retained history > since_rv."""
        w = Watch(self, resource, cluster, namespace, selector or everything())
        if since_rv is not None and since_rv < self._rv:
            # the retained history must cover (since_rv, now]; otherwise the
            # caller missed events it can never recover (e.g. resuming a
            # pre-restart RV against a WAL-restored store) and must re-list
            oldest = self._history[0].rv if self._history else None
            if oldest is None or oldest > since_rv + 1:
                raise ConflictError(
                    f"watch window expired: requested rv {since_rv}, oldest retained {oldest}"
                )
            for ev in self._history:
                if ev.rv > since_rv:
                    out = w._transform(ev)
                    if out is not None:
                        w._push(out)
        self._watches.append(w)
        return w

    def _emit(self, etype: str, key: Key, obj: dict, rv: int, old: dict | None = None) -> None:
        ev = Event(
            etype, key[0], key[1], key[2], key[3], copy.deepcopy(obj), rv,
            copy.deepcopy(old) if old is not None else None,
        )
        self._history.append(ev)
        # snapshot: an injected watch drop closes (and unsubscribes) the
        # watch from inside _push, mid-iteration
        for w in list(self._watches):
            out = w._transform(ev)
            if out is not None:
                w._push(out)

    def _unsubscribe(self, w: Watch) -> None:
        try:
            self._watches.remove(w)
        except ValueError:
            pass

    # ---------------------------------------------------------- durability

    def _log_wal(self, rec: dict) -> None:
        if self._engine is not None:
            key = _wal_key(tuple(rec["key"]))
            if rec["op"] == "put":
                self._engine.put(
                    key,
                    json.dumps(rec["obj"], separators=(",", ":")).encode("utf-8"),
                    rec["rv"],
                )
            else:
                self._engine.delete(key, rec["rv"])
            self._engine_mutations += 1
            if self._engine_mutations >= self._engine_snapshot_every:
                self.snapshot()
            return
        if self._wal is None or self._wal.fh is None:
            return
        self._wal.fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.fh.flush()
        self._wal.mutations_since_snapshot += 1
        if self._wal.mutations_since_snapshot >= self._wal.snapshot_every:
            self.snapshot()

    def _load_engine(self) -> None:
        assert self._engine is not None
        for key, val in self._engine.scan():
            parts = tuple(key.decode("utf-8").split("\x00"))
            self._objects[parts] = json.loads(val)
        self._rv = self._engine.rv
        # journal-only mode: this store holds the authoritative objects,
        # so the engine's duplicate value map would only double memory
        self._engine.release_index()

    def _load_wal(self) -> None:
        assert self._wal is not None
        snap = self._wal.path + ".snap"
        if os.path.exists(snap):
            with open(snap, encoding="utf-8") as f:
                data = json.load(f)
            self._rv = data["rv"]
            for rec in data["objects"]:
                self._objects[tuple(rec["key"])] = rec["obj"]
        if os.path.exists(self._wal.path):
            with open(self._wal.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    key = tuple(rec["key"])
                    if rec["op"] == "put":
                        self._objects[key] = rec["obj"]
                    elif rec["op"] == "del":
                        self._objects.pop(key, None)
                    self._rv = max(self._rv, rec.get("rv", 0))

    def snapshot(self) -> None:
        """Write a snapshot and truncate the WAL (etcd compaction analog)."""
        if self._engine is not None:
            self._engine.snapshot_stream(
                (_wal_key(k), json.dumps(v, separators=(",", ":")).encode("utf-8"))
                for k, v in self._objects.items()
            )
            self._engine_mutations = 0
            return
        if self._wal is None:
            return
        snap = self._wal.path + ".snap"
        tmp = snap + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "rv": self._rv,
                    "objects": [
                        {"key": list(k), "obj": v} for k, v in self._objects.items()
                    ],
                },
                f,
            )
        os.replace(tmp, snap)
        if self._wal.fh is not None:
            self._wal.fh.close()
        self._wal.fh = open(self._wal.path, "w", encoding="utf-8")
        self._wal.mutations_since_snapshot = 0

    def close(self) -> None:
        for w in list(self._watches):
            w.close()
        if self._engine is not None:
            self._engine.close()
            self._engine = None
        if self._wal is not None and self._wal.fh is not None:
            self._wal.fh.close()
            self._wal.fh = None

    # ----------------------------------------------------------- internal

    @staticmethod
    def _non_status_changed(a: Mapping, b: Mapping) -> bool:
        """True when anything outside .status and volatile metadata differs.

        The host-side twin of the device diff kernel's spec lane
        (reference behavior: pkg/syncer/specsyncer.go:17-41
        deepEqualApartFromStatus ignores status + mutable metadata).
        """

        def strip(o: Mapping) -> dict:
            o = {k: v for k, v in o.items() if k != "status"}
            meta = dict(o.get("metadata") or {})
            for f in ("resourceVersion", "generation", "managedFields", "creationTimestamp", "uid"):
                meta.pop(f, None)
            o["metadata"] = meta
            return o

        return strip(a) != strip(b)


def iter_keys(store: LogicalStore) -> Iterator[Key]:
    return iter(store._objects.keys())
