"""RemoteStore — serve this process against another server's storage.

The reference's ``kcp start --etcd-servers`` skips the embedded etcd and
points the apiserver at shared external storage (reference:
pkg/server/server.go:263-291), so several frontends can serve one
dataset. The analog here: a :class:`RemoteStore` implements the
:class:`~kcp_tpu.store.store.LogicalStore` verb surface by delegating
every call to a *backend* kcp-tpu server over its REST API
(``kcp start --store-server https://backend:6443``). Storage semantics —
RV allocation, conflict detection, generation bumps, finalizers, watch
history windows — are enforced once, by the backend's real store; this
class is a transport, not a second implementation.

Division of labor when a frontend serves this way:
- reads/writes/watches pass through (one RestClient per logical cluster,
  kept-alive; watches ride the ndjson stream);
- the frontend runs NO WAL and takes no snapshots (``snapshot`` is a
  no-op) — durability is the backend's;
- controllers: run them on exactly one process (usually the backend;
  start frontends with --no-install-controllers) or they will fight over
  the same objects, the same rule the reference has for running several
  kcp replicas against one etcd.

Caveat vs the in-process store: an expired watch window surfaces as a
``ConflictError`` on the first iteration of the returned watch rather
than synchronously from :meth:`watch` (the stream error arrives with the
backend's response) — informer relists handle both shapes.
"""

from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict

from ..analysis.sanitize import make_lock
from .selectors import LabelSelector
from .store import WILDCARD

DEFAULT_CLUSTER = "default"


class ConnectionPool:
    """Bounded pool of RestClients for ONE peer (a shard behind the
    router, a storage backend): each client owns one kept-alive
    connection and is not thread-safe, so concurrency = clients. All
    clients are ``scoped()`` clones of one prototype, which makes the
    per-peer circuit breaker and the discovery cache SHARED — a dead
    peer trips once and every borrowed client fails fast.

    ``client()`` is a context manager: borrow (blocking once ``cap``
    clients are all in flight — backpressure instead of unbounded
    sockets), use, return. Used by the shard router for scatter-gather
    fan-out, where N shards × M concurrent requests would otherwise
    serialize on one connection per shard."""

    def __init__(self, base_url: str, token: str = "",
                 ca_data: bytes | str | None = None,
                 ca_file: str | None = None, cap: int = 8,
                 cluster: str = WILDCARD):
        # deferred import: store/ must not import server/ at module load
        from ..server.rest import RestClient

        self._proto = RestClient(base_url, cluster=cluster, token=token,
                                 ca_data=ca_data, ca_file=ca_file)
        self._cap = max(1, cap)
        self._cond = threading.Condition()
        self._free = [self._proto]
        self._total = 1
        self._closed = False
        self.base_url = base_url

    @property
    def breaker(self):
        """The peer's shared circuit breaker (one per pool)."""
        return self._proto._breaker

    @property
    def ssl_context(self):
        return self._proto._ssl

    @property
    def token(self) -> str:
        return self._proto.token

    @contextlib.contextmanager
    def client(self):
        with self._cond:
            while not self._free and self._total >= self._cap:
                if not self._cond.wait(timeout=30):
                    raise TimeoutError(
                        f"connection pool for {self.base_url} exhausted "
                        f"({self._cap} clients all in flight for 30s)")
            if self._free:
                c = self._free.pop()
            else:
                c = self._proto.scoped(self._proto.cluster)
                self._total += 1
        try:
            yield c
        finally:
            with self._cond:
                if self._closed:
                    c.close()
                else:
                    self._free.append(c)
                self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            free, self._free = self._free, []
            self._cond.notify_all()
        for c in free:
            c.close()


class RemoteStore:
    """LogicalStore-surface adapter over a backend server's REST API."""

    # handler capability flag: verbs are blocking network I/O (offload
    # from the serving loop) and the backend resolves wildcard reads
    # itself (skip the local tenant scan)
    is_remote = True

    def __init__(self, base_url: str, token: str = "",
                 ca_data: bytes | str | None = None,
                 ca_file: str | None = None):
        # deferred import: store/ must not import server/ at module load
        # (server imports store)
        from ..server.rest import RestClient

        self._root = RestClient(base_url, cluster=WILDCARD, token=token,
                                ca_data=ca_data, ca_file=ca_file)
        # Callers run verbs from a thread pool (the handler's store-I/O
        # executor), but each RestClient owns ONE kept-alive connection
        # and is not thread-safe — so every entry pairs a client with a
        # lock, concurrency comes from different clusters proceeding in
        # parallel, and the LRU map itself is guarded by _map_lock.
        # Bounded so a frontend asked about arbitrarily many tenants
        # doesn't leak a socket per tenant. The discovery cache the
        # scoped clients share is the one piece of cross-entry state;
        # RestClient guards it with its own _disc_lock (no GIL
        # assumption — see rest.py), so per-entry locks stay strictly
        # about the connection.
        self._map_lock = make_lock("remote.scope_map")
        self._scoped: "OrderedDict[str, tuple[object, threading.Lock]]" = (
            OrderedDict({WILDCARD: (self._root, make_lock("remote.scoped_conn"))}))
        self._scoped_cap = 256
        self.base_url = base_url
        # LogicalStore duck-type attributes the handler/client read
        self.openapi_doc: dict | None = None
        self.namespace_lifecycle = False  # backend stamps finalizers

    # ---------------------------------------------------------- plumbing

    def _entry(self, cluster: str):
        with self._map_lock:
            e = self._scoped.get(cluster)
            if e is None:
                e = (self._root.scoped(cluster), make_lock("remote.scoped_conn"))
                self._scoped[cluster] = e
                if len(self._scoped) > self._scoped_cap:
                    key, (evicted, elock) = self._scoped.popitem(last=False)
                    if key == WILDCARD:
                        # the root entry is load-bearing (RV/cluster
                        # probes) — never evict it: re-insert as
                        # most-recent and take the true oldest instead
                        self._scoped[WILDCARD] = (evicted, elock)
                        key, (evicted, elock) = self._scoped.popitem(last=False)
                    # close only if idle; a client mid-request keeps its
                    # socket until GC finalizes it (never yank a
                    # connection out from under another thread)
                    if elock.acquire(blocking=False):
                        try:
                            evicted.close()
                        finally:
                            elock.release()
            else:
                self._scoped.move_to_end(cluster)
            return e

    def _call(self, cluster: str, verb: str, *args, **kwargs):
        client, lock = self._entry(cluster)
        with lock:
            return getattr(client, verb)(*args, **kwargs)

    # ------------------------------------------------------------- verbs

    def create(self, resource: str, cluster: str, obj: dict,
               namespace: str = "") -> dict:
        return self._call(cluster, "create", resource, obj, namespace)

    def get(self, resource: str, cluster: str, name: str,
            namespace: str = "") -> dict:
        return self._call(cluster, "get", resource, name, namespace)

    def update(self, resource: str, cluster: str, obj: dict,
               namespace: str = "", subresource: str | None = None) -> dict:
        if subresource == "status":
            return self._call(cluster, "update_status", resource, obj, namespace)
        if subresource is not None:
            raise ValueError(f"unknown subresource {subresource!r}")
        return self._call(cluster, "update", resource, obj, namespace)

    def update_status(self, resource: str, cluster: str, obj: dict,
                      namespace: str = "") -> dict:
        return self.update(resource, cluster, obj, namespace,
                           subresource="status")

    def delete(self, resource: str, cluster: str, name: str,
               namespace: str = "") -> None:
        client, lock = self._entry(cluster)
        with lock:
            if cluster == WILDCARD:
                # RestClient refuses wildcard deletes (an in-process
                # store needs an explicit tenant), but here the backend's
                # handler resolves '*' to the unique owner exactly as a
                # frontend would have — forward it
                client._request(
                    "DELETE",
                    client._path(resource, namespace, name, cluster=cluster))
                return
            client.delete(resource, name, namespace, cluster=cluster)

    def list(self, resource: str, cluster: str = WILDCARD,
             namespace: str | None = None,
             selector: LabelSelector | None = None) -> tuple[list[dict], int]:
        return self._call(cluster, "list", resource, namespace, selector)

    def watch(self, resource: str, cluster: str = WILDCARD,
              namespace: str | None = None,
              selector: LabelSelector | None = None,
              since_rv: int | None = None):
        # watch construction may refresh discovery (a blocking request)
        # before returning the lazily-connecting RestWatch, so it holds
        # the cluster lock like any other verb
        return self._call(cluster, "watch", resource, namespace, selector,
                          since_rv=since_rv)

    # --------------------------------------------------------- inventory

    @property
    def resource_version(self) -> int:
        client, lock = self._entry(WILDCARD)
        with lock:
            body = client._request("GET", "/version")
        if "resourceVersion" not in body:
            # an authz'd backend withholds the RV from tokens lacking the
            # server-global read — returning 0 here would poison watch
            # bookmarks with a rewind-to-zero, so fail loudly instead
            raise RuntimeError(
                "storage backend withheld resourceVersion from /version — "
                "the --store-token needs the server-global (wildcard get "
                "debug) read that /clusters and /debug carry")
        return int(body["resourceVersion"])

    def resources(self) -> list[str]:
        return self._call(WILDCARD, "resources")

    def clusters(self) -> list[str]:
        client, lock = self._entry(WILDCARD)
        with lock:
            body = client._request("GET", "/clusters")
        return list(body.get("clusters", []))

    def __len__(self) -> int:
        # only inventory surfaces (kcp snapshot) use this; a wildcard
        # list per resource is acceptable there and wrong to cache
        return sum(len(self.list(r)[0]) for r in self.resources())

    # ---------------------------------------------------------- lifecycle

    def snapshot(self) -> None:
        """No-op: durability belongs to the backend's store."""

    def close(self) -> None:
        with self._map_lock:
            entries = list(self._scoped.values())
        for client, lock in entries:
            with lock:
                client.close()
