"""RemoteStore — serve this process against another server's storage.

The reference's ``kcp start --etcd-servers`` skips the embedded etcd and
points the apiserver at shared external storage (reference:
pkg/server/server.go:263-291), so several frontends can serve one
dataset. The analog here: a :class:`RemoteStore` implements the
:class:`~kcp_tpu.store.store.LogicalStore` verb surface by delegating
every call to a *backend* kcp-tpu server over its REST API
(``kcp start --store-server https://backend:6443``). Storage semantics —
RV allocation, conflict detection, generation bumps, finalizers, watch
history windows — are enforced once, by the backend's real store; this
class is a transport, not a second implementation.

Division of labor when a frontend serves this way:
- reads/writes/watches pass through a bounded :class:`ConnectionPool`
  whose kept-alive connections are re-scoped per borrow (one socket
  serves every tenant; watches ride the ndjson stream);
- the frontend runs NO WAL and takes no snapshots (``snapshot`` is a
  no-op) — durability is the backend's;
- controllers: run them on exactly one process (usually the backend;
  start frontends with --no-install-controllers) or they will fight over
  the same objects, the same rule the reference has for running several
  kcp replicas against one etcd.

Caveat vs the in-process store: an expired watch window surfaces as a
``ConflictError`` on the first iteration of the returned watch rather
than synchronously from :meth:`watch` (the stream error arrives with the
backend's response) — informer relists handle both shapes.
"""

from __future__ import annotations

import contextlib
import os
import threading

from ..utils.errors import UnavailableError
from .selectors import LabelSelector
from .store import WILDCARD

DEFAULT_CLUSTER = "default"


class ConnectionPool:
    """Bounded pool of RestClients for ONE peer (a shard behind the
    router, a storage backend, a smart client's direct shard): each
    client owns one kept-alive connection and is not thread-safe, so
    concurrency = clients. All clients are ``scoped()`` clones of one
    prototype, which makes the per-peer circuit breaker and the
    discovery cache SHARED — a dead peer trips once and every borrowed
    client fails fast.

    ``client(cluster=...)`` is a context manager: borrow (blocking once
    every in-flight slot is taken — backpressure instead of unbounded
    sockets), use, return. Passing ``cluster`` re-scopes the borrowed
    client in place: the SAME kept-alive connection serves every
    logical-cluster scope over its lifetime (connection reuse across
    scoped clones — a frontend asked about 10k tenants holds ``cap``
    sockets, not 10k).

    ``depth`` (``KCP_ROUTER_POOL_DEPTH``, default 1) is the burst
    multiplexing knob: up to ``cap × depth`` borrows may be in flight at
    once. The first ``cap`` ride the kept-alive pooled connections;
    bursts beyond that get transient clients whose connections close on
    return — bounded socket growth under fan-out spikes instead of a
    30 s borrow stall. ``depth=1`` is exactly the legacy blocking pool."""

    def __init__(self, base_url: str, token: str = "",
                 ca_data: bytes | str | None = None,
                 ca_file: str | None = None, cap: int = 8,
                 cluster: str = WILDCARD, depth: int | None = None):
        # deferred import: store/ must not import server/ at module load
        from ..server.rest import RestClient

        self._proto = RestClient(base_url, cluster=cluster, token=token,
                                 ca_data=ca_data, ca_file=ca_file)
        self._cap = max(1, cap)
        if depth is None:
            depth = int(os.environ.get("KCP_ROUTER_POOL_DEPTH", "1") or "1")
        self._depth = max(1, depth)
        self._max_inflight = self._cap * self._depth
        self._cond = threading.Condition()
        self._free = [self._proto]
        self._total = 1          # pooled (kept-alive) clients created
        self._inflight = 0       # borrows currently outstanding
        self._closed = False
        self.base_url = base_url

    @property
    def breaker(self):
        """The peer's shared circuit breaker (one per pool)."""
        return self._proto._breaker

    @property
    def ssl_context(self):
        return self._proto._ssl

    @property
    def token(self) -> str:
        return self._proto.token

    @contextlib.contextmanager
    def client(self, cluster: str | None = None):
        transient = False
        with self._cond:
            if self._closed:
                # a retired/closed pool must not mint fresh sockets —
                # typed so the router's fail-fast path and the smart
                # client's fallback both handle it like a dead peer
                raise UnavailableError(
                    f"connection pool for {self.base_url} is closed")
            while (not self._free and self._total >= self._cap
                   and self._inflight >= self._max_inflight):
                if not self._cond.wait(timeout=30):
                    raise TimeoutError(
                        f"connection pool for {self.base_url} exhausted "
                        f"({self._max_inflight} borrows all in flight "
                        f"for 30s)")
            if self._free:
                c = self._free.pop()
            elif self._total < self._cap:
                c = self._proto.scoped(self._proto.cluster)
                self._total += 1
            else:
                # burst beyond the kept-alive core (depth > 1): a
                # transient clone — same breaker/discovery, its own
                # connection, closed on return
                c = self._proto.scoped(self._proto.cluster)
                transient = True
            self._inflight += 1
        if cluster is not None and c.cluster != cluster:
            # connection reuse across scoped clones: re-scope in place —
            # the borrow is exclusive, so mutating the clone is safe
            c.cluster = cluster
        try:
            yield c
        finally:
            with self._cond:
                self._inflight -= 1
                if self._closed or transient:
                    c.close()
                else:
                    self._free.append(c)
                self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            free, self._free = self._free, []
            self._cond.notify_all()
        for c in free:
            c.close()


class RemoteStore:
    """LogicalStore-surface adapter over a backend server's REST API."""

    # handler capability flag: verbs are blocking network I/O (offload
    # from the serving loop) and the backend resolves wildcard reads
    # itself (skip the local tenant scan)
    is_remote = True

    def __init__(self, base_url: str, token: str = "",
                 ca_data: bytes | str | None = None,
                 ca_file: str | None = None):
        # Callers run verbs from a thread pool (the handler's store-I/O
        # executor), but each RestClient owns ONE kept-alive connection
        # and is not thread-safe — so verbs borrow from a bounded
        # ConnectionPool and re-scope the borrowed client to the target
        # cluster in place. One connection serves EVERY tenant scope
        # over its lifetime (the pre-PR 13 shape held a kept-alive
        # socket per tenant in a 256-entry LRU; a frontend asked about
        # 10k tenants now holds `cap` sockets, period). The discovery
        # cache and the per-peer circuit breaker are shared across the
        # pool's clones by RestClient.scoped's own contract.
        self._pool = ConnectionPool(
            base_url, token=token, ca_data=ca_data, ca_file=ca_file,
            cap=int(os.environ.get("KCP_ROUTER_POOL", "8") or "8"),
            cluster=WILDCARD)
        self.base_url = base_url
        # LogicalStore duck-type attributes the handler/client read
        self.openapi_doc: dict | None = None
        self.namespace_lifecycle = False  # backend stamps finalizers

    # ---------------------------------------------------------- plumbing

    def _call(self, cluster: str, verb: str, *args, **kwargs):
        with self._pool.client(cluster) as c:
            return getattr(c, verb)(*args, **kwargs)

    # ------------------------------------------------------------- verbs

    def create(self, resource: str, cluster: str, obj: dict,
               namespace: str = "") -> dict:
        return self._call(cluster, "create", resource, obj, namespace)

    def get(self, resource: str, cluster: str, name: str,
            namespace: str = "") -> dict:
        return self._call(cluster, "get", resource, name, namespace)

    def update(self, resource: str, cluster: str, obj: dict,
               namespace: str = "", subresource: str | None = None) -> dict:
        if subresource == "status":
            return self._call(cluster, "update_status", resource, obj, namespace)
        if subresource is not None:
            raise ValueError(f"unknown subresource {subresource!r}")
        return self._call(cluster, "update", resource, obj, namespace)

    def update_status(self, resource: str, cluster: str, obj: dict,
                      namespace: str = "") -> dict:
        return self.update(resource, cluster, obj, namespace,
                           subresource="status")

    def delete(self, resource: str, cluster: str, name: str,
               namespace: str = "") -> None:
        with self._pool.client(cluster) as client:
            if cluster == WILDCARD:
                # RestClient refuses wildcard deletes (an in-process
                # store needs an explicit tenant), but here the backend's
                # handler resolves '*' to the unique owner exactly as a
                # frontend would have — forward it
                client._request(
                    "DELETE",
                    client._path(resource, namespace, name, cluster=cluster))
                return
            client.delete(resource, name, namespace, cluster=cluster)

    def list(self, resource: str, cluster: str = WILDCARD,
             namespace: str | None = None,
             selector: LabelSelector | None = None) -> tuple[list[dict], int]:
        return self._call(cluster, "list", resource, namespace, selector)

    def watch(self, resource: str, cluster: str = WILDCARD,
              namespace: str | None = None,
              selector: LabelSelector | None = None,
              since_rv: int | None = None):
        # watch construction may refresh discovery (a blocking request)
        # before returning the lazily-connecting RestWatch, so it holds
        # the cluster lock like any other verb
        return self._call(cluster, "watch", resource, namespace, selector,
                          since_rv=since_rv)

    # --------------------------------------------------------- inventory

    @property
    def resource_version(self) -> int:
        with self._pool.client(WILDCARD) as client:
            body = client._request("GET", "/version")
        if "resourceVersion" not in body:
            # an authz'd backend withholds the RV from tokens lacking the
            # server-global read — returning 0 here would poison watch
            # bookmarks with a rewind-to-zero, so fail loudly instead
            raise RuntimeError(
                "storage backend withheld resourceVersion from /version — "
                "the --store-token needs the server-global (wildcard get "
                "debug) read that /clusters and /debug carry")
        return int(body["resourceVersion"])

    def resources(self) -> list[str]:
        return self._call(WILDCARD, "resources")

    def clusters(self) -> list[str]:
        with self._pool.client(WILDCARD) as client:
            body = client._request("GET", "/clusters")
        return list(body.get("clusters", []))

    def __len__(self) -> int:
        # only inventory surfaces (kcp snapshot) use this; a wildcard
        # list per resource is acceptable there and wrong to cache
        return sum(len(self.list(r)[0]) for r in self.resources())

    # ---------------------------------------------------------- lifecycle

    def snapshot(self) -> None:
        """No-op: durability belongs to the backend's store."""

    def close(self) -> None:
        self._pool.close()
