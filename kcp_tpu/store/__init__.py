from .selectors import LabelSelector, parse_selector
from .store import WILDCARD, Event, LogicalStore, Watch

__all__ = [
    "LogicalStore",
    "Event",
    "Watch",
    "WILDCARD",
    "LabelSelector",
    "parse_selector",
]
