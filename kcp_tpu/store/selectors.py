"""Label selectors: parsing and matching.

The host-side reference semantics for the device-side label-match kernel
(kcp_tpu/ops/labelmatch.py). The reference relies on upstream Kubernetes
label selectors; the subset implemented here covers everything the
reference itself uses (plain equality, e.g. ``kcp.dev/cluster=<id>`` at
pkg/syncer/syncer.go:106-108) plus the standard set-based operators so the
framework is usable as a general control plane.

Grammar (comma = AND):
    key=value | key==value | key!=value
    key in (v1,v2) | key notin (v1,v2)
    key            (exists)
    !key           (not exists)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Iterable, Mapping

_SET_RE = re.compile(r"^\s*(?P<key>[^!=\s]+)\s+(?P<op>in|notin)\s+\((?P<vals>[^)]*)\)\s*$")


@dataclass(frozen=True)
class Requirement:
    key: str
    op: str  # "=", "!=", "in", "notin", "exists", "!exists"
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        present = self.key in labels
        if self.op == "exists":
            return present
        if self.op == "!exists":
            return not present
        if self.op == "=":
            return present and labels[self.key] == self.values[0]
        if self.op == "!=":
            # Kubernetes semantics: absent key satisfies !=
            return not present or labels[self.key] != self.values[0]
        if self.op == "in":
            return present and labels[self.key] in self.values
        if self.op == "notin":
            return not present or labels[self.key] not in self.values
        raise ValueError(f"unknown selector op {self.op!r}")


@dataclass(frozen=True)
class LabelSelector:
    requirements: tuple[Requirement, ...] = field(default_factory=tuple)

    def matches(self, labels: Mapping[str, str] | None) -> bool:
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    @property
    def empty(self) -> bool:
        return not self.requirements

    @property
    def single_equality(self) -> tuple[str, str] | None:
        """(key, value) when this is exactly one ``=`` requirement — the
        dominant watch shape (the syncer registers one
        ``kcp.dev/cluster=<id>`` per cluster) and the one the batched
        fan-out can answer with a single pair-presence compare
        (ops/labelmatch.fanout_match)."""
        if len(self.requirements) == 1:
            r = self.requirements[0]
            if r.op == "=" and len(r.values) == 1:
                return (r.key, r.values[0])
        return None

    def __str__(self) -> str:
        parts = []
        for r in self.requirements:
            if r.op == "exists":
                parts.append(r.key)
            elif r.op == "!exists":
                parts.append(f"!{r.key}")
            elif r.op in ("in", "notin"):
                parts.append(f"{r.key} {r.op} ({','.join(r.values)})")
            else:
                parts.append(f"{r.key}{r.op}{r.values[0]}")
        return ",".join(parts)


def everything() -> LabelSelector:
    return LabelSelector(())


def parse_selector(spec: str | None) -> LabelSelector:
    """Parse a selector string. Empty/None selects everything."""
    if not spec or not spec.strip():
        return everything()
    reqs: list[Requirement] = []
    for raw in _split_top_level(spec):
        term = raw.strip()
        if not term:
            continue
        m = _SET_RE.match(term)
        if m:
            vals = tuple(v.strip() for v in m.group("vals").split(",") if v.strip())
            reqs.append(Requirement(m.group("key"), m.group("op"), vals))
        elif "!=" in term:
            key, _, val = term.partition("!=")
            reqs.append(Requirement(key.strip(), "!=", (val.strip(),)))
        elif "==" in term:
            key, _, val = term.partition("==")
            reqs.append(Requirement(key.strip(), "=", (val.strip(),)))
        elif "=" in term:
            key, _, val = term.partition("=")
            reqs.append(Requirement(key.strip(), "=", (val.strip(),)))
        elif term.startswith("!"):
            reqs.append(Requirement(term[1:].strip(), "!exists"))
        else:
            reqs.append(Requirement(term, "exists"))
    return LabelSelector(tuple(reqs))


def _split_top_level(spec: str) -> Iterable[str]:
    """Split on commas that are not inside ``in (...)`` value lists."""
    depth = 0
    start = 0
    for i, ch in enumerate(spec):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        elif ch == "," and depth == 0:
            yield spec[start:i]
            start = i + 1
    yield spec[start:]


def selector_from_dict(sel: Mapping | None) -> LabelSelector:
    """Build a selector from the k8s ``{matchLabels, matchExpressions}`` form."""
    if not sel:
        return everything()
    reqs: list[Requirement] = []
    for k, v in (sel.get("matchLabels") or {}).items():
        reqs.append(Requirement(k, "=", (str(v),)))
    for expr in sel.get("matchExpressions") or []:
        op = expr.get("operator", "")
        key = expr["key"]
        vals = tuple(str(v) for v in expr.get("values") or ())
        if op == "In":
            reqs.append(Requirement(key, "in", vals))
        elif op == "NotIn":
            reqs.append(Requirement(key, "notin", vals))
        elif op == "Exists":
            reqs.append(Requirement(key, "exists"))
        elif op == "DoesNotExist":
            reqs.append(Requirement(key, "!exists"))
        else:
            raise ValueError(f"unknown matchExpressions operator {op!r}")
    return LabelSelector(tuple(reqs))
