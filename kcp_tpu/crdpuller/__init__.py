from .puller import SchemaPuller

__all__ = ["SchemaPuller"]
