"""Schema puller: physical-cluster discovery -> CRD synthesis.

The analog of the reference's crd-puller (pkg/crdpuller/discovery.go):
given a client to a physical cluster, produce a CRD for each requested
resource, either from a CRD the cluster already defines or synthesized
from discovery metadata plus known schemas (the reference hardcodes
schemas for meta types in ``knownPackages``, discovery.go:481-569; here
the known-schema table covers the core types the demos sync).

The puller works against any Client (in-process fake physical cluster or
the REST client), which is what makes kind-free end-to-end tests possible
(SURVEY.md §4 implication).
"""

from __future__ import annotations

import copy
import logging

from ..apis import crd as crdapi
from ..apis.scheme import GVR
from ..client import Client
from ..utils import errors
from . import openapi

log = logging.getLogger(__name__)

# Minimal structural schemas for well-known types (knownPackages analog).
_STRING = {"type": "string"}
_INT = {"type": "integer"}
_OBJECT_PRESERVE = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
_STRING_MAP = {"type": "object", "additionalProperties": {"type": "string"}}

KNOWN_SCHEMAS: dict[str, dict] = {
    "configmaps": {
        "type": "object",
        "properties": {
            "apiVersion": _STRING,
            "kind": _STRING,
            "metadata": _OBJECT_PRESERVE,
            "data": _STRING_MAP,
            "binaryData": _STRING_MAP,
            "immutable": {"type": "boolean"},
        },
    },
    "secrets": {
        "type": "object",
        "properties": {
            "apiVersion": _STRING,
            "kind": _STRING,
            "metadata": _OBJECT_PRESERVE,
            "data": _STRING_MAP,
            "stringData": _STRING_MAP,
            "type": _STRING,
            "immutable": {"type": "boolean"},
        },
    },
    "deployments": {
        "type": "object",
        "properties": {
            "apiVersion": _STRING,
            "kind": _STRING,
            "metadata": _OBJECT_PRESERVE,
            "spec": {
                "type": "object",
                "properties": {
                    "replicas": _INT,
                    "selector": _OBJECT_PRESERVE,
                    "template": _OBJECT_PRESERVE,
                    "strategy": _OBJECT_PRESERVE,
                    "minReadySeconds": _INT,
                    "paused": {"type": "boolean"},
                },
            },
            "status": {
                "type": "object",
                "properties": {
                    "replicas": _INT,
                    "updatedReplicas": _INT,
                    "readyReplicas": _INT,
                    "availableReplicas": _INT,
                    "unavailableReplicas": _INT,
                    "observedGeneration": _INT,
                    "conditions": {"type": "array", "items": _OBJECT_PRESERVE},
                },
            },
        },
    },
    "services": {
        "type": "object",
        "properties": {
            "apiVersion": _STRING,
            "kind": _STRING,
            "metadata": _OBJECT_PRESERVE,
            "spec": _OBJECT_PRESERVE,
            "status": _OBJECT_PRESERVE,
        },
    },
    "pods": {
        "type": "object",
        "properties": {
            "apiVersion": _STRING,
            "kind": _STRING,
            "metadata": _OBJECT_PRESERVE,
            "spec": _OBJECT_PRESERVE,
            "status": _OBJECT_PRESERVE,
        },
    },
}


class SchemaPuller:
    """Pulls CRDs for named resources from a physical cluster client."""

    def __init__(self, physical: Client):
        self.physical = physical

    def pull_crds(self, resources: list[str]) -> dict[str, dict | None]:
        """resource name (``plural`` or ``plural.group``) -> CRD dict or
        None when the cluster doesn't serve it (reference: PullCRDs,
        discovery.go:85-287)."""
        out: dict[str, dict | None] = {}
        doc = self._fetch_openapi()  # once per pass (discovery.go:60-66)
        for res in resources:
            gvr = GVR.parse(res)
            crd = self._from_existing_crd(gvr)
            if crd is None:
                crd = self._synthesize(gvr, doc)
            out[res] = crd
        return out

    def _from_existing_crd(self, gvr: GVR) -> dict | None:
        """The cluster defines this resource as a CRD: pull it as-is
        (discovery.go:157-175)."""
        name = crdapi.crd_name(gvr.resource, gvr.group)
        try:
            crd = self.physical.get(crdapi.CRDS, name)
        except errors.NotFoundError:
            return None
        crd = copy.deepcopy(crd)
        crd["metadata"] = {"name": name}
        crd.pop("status", None)
        return crd

    def _synthesize(self, gvr: GVR, doc: dict | None) -> dict | None:
        """Discovery -> synthesized CRD (discovery.go:176-287).

        Schema source precedence matches the reference: the cluster's
        LIVE ``/openapi/v2`` document wins (SchemaConverter analog,
        :mod:`.openapi` — its known-ref tables override meta-type $refs
        INSIDE the conversion, discovery.go:481-569), then the curated
        resource-level table (for clusters serving no usable openapi),
        then preserve-unknown. A physical cluster's actual schema for a
        well-known resource name must be importable — the curated table
        is a fallback, not a shadow.
        """
        info = self.physical.scheme.by_resource(gvr.storage_name)
        if info is None or gvr.storage_name not in self.physical.resources():
            return None
        schema = self._from_openapi(info, doc)
        if schema is None and gvr.resource in KNOWN_SCHEMAS:
            schema = copy.deepcopy(KNOWN_SCHEMAS[gvr.resource])
        if schema is None:
            schema = copy.deepcopy(_OBJECT_PRESERVE)
        has_status = "status" in (schema.get("properties") or {})
        if not has_status and gvr.resource in KNOWN_SCHEMAS:
            # the reference derives the status subresource from discovery
            # (discovery.go:214-224); our discovery surface has no
            # per-subresource signal, so well-known resources keep their
            # curated status guarantee even when the live openapi
            # definition omits the property
            has_status = "status" in (
                KNOWN_SCHEMAS[gvr.resource].get("properties") or {})
        return crdapi.new_crd(
            group=info.gvr.group,
            version=info.gvr.version,
            plural=info.gvr.resource,
            kind=info.kind,
            scope="Namespaced" if info.namespaced else "Cluster",
            schema=schema,
            subresources={"status": {}} if has_status else None,
        )

    def _fetch_openapi(self) -> dict | None:
        """The cluster's swagger document, fetched once per pull pass
        (the reference loads openapi models once at puller construction,
        discovery.go:60-66)."""
        getter = getattr(self.physical, "openapi_v2", None)
        if getter is None:
            return None
        try:
            return getter()
        except errors.ApiError:
            return None

    def _from_openapi(self, info, doc: dict | None) -> dict | None:
        """Synthesize a structural schema from the swagger document, or
        None when the document is absent, carries no definition for the
        GVK, or the definition cannot convert (recursive refs etc. —
        discovery.go:200-206 skips such types; here the next fallback
        applies instead)."""
        if not doc:
            return None
        def_name = openapi.definition_for_gvk(
            doc, info.gvr.group, info.gvr.version, info.kind)
        if def_name is None:
            return None
        try:
            return openapi.convert_definition(doc, def_name)
        except openapi.ConversionError as e:
            log.warning("openapi conversion for %s failed (%s); falling back",
                        info.gvr.storage_name, e)
            return None
