"""OpenAPI v2 (swagger) -> structural CRD schema synthesis.

The analog of the reference's ``SchemaConverter`` proto visitor
(pkg/crdpuller/discovery.go:289-475): given a physical cluster's
``/openapi/v2`` document, synthesize a structural JSON schema for an
arbitrary resource type so the API importer can feed real (non
preserve-unknown) schemas into LCD negotiation. Where the reference
visits kube-openapi proto models, this walks the raw swagger JSON —
same semantics, no proto dependency:

- ``$ref`` resolution with cycle detection (recursive schemas are an
  error, discovery.go:442-447)
- hardcoded overrides for well-known meta types (the ``knownSchemas``
  table, discovery.go:481-569) keyed by definition-name suffix
- the top-level ``metadata`` field collapses to a bare object
  (discovery.go:424-426)
- array merge/list extensions map onto ``x-kubernetes-list-type`` /
  ``x-kubernetes-list-map-keys`` (discovery.go:336-395)
- typeless/propertyless subtrees become embedded resources
  (``x-kubernetes-embedded-resource``) with preserve-unknown defaulting
  to true — a deliberate deviation from VisitArbitrary
  (discovery.go:325-335), whose exact output is invalid under
  Kubernetes structural-schema rules and fails the reference's own
  schemacompat dispatch (schemacompat.go:144-165)
- inline ``x-kubernetes-int-or-string`` / preserve-unknown extensions
  pass through, so CRD-derived documents (a kcp serving published CRDs
  as swagger) round-trip without degradation
"""

from __future__ import annotations

import copy
from typing import Any

REF_PREFIX = "#/definitions/"
GVK_EXT = "x-kubernetes-group-version-kind"
INT_OR_STRING = "x-kubernetes-int-or-string"
PRESERVE_UNKNOWN = "x-kubernetes-preserve-unknown-fields"

# knownSchemas analog (discovery.go:481-569): schemas for meta types that
# either can't round-trip through swagger (Quantity, IntOrString) or that
# CRDs must not constrain (RawExtension, ObjectMeta). Matched on the
# swagger definition-name suffix.
KNOWN_REF_SCHEMAS: dict[str, dict] = {
    ".ObjectMeta": {"type": "object"},
    ".Time": {"type": "string", "format": "date-time"},
    ".MicroTime": {"type": "string", "format": "date-time"},
    ".Duration": {"type": "string"},
    ".Quantity": {"x-kubernetes-int-or-string": True},
    ".IntOrString": {"x-kubernetes-int-or-string": True},
    ".RawExtension": {
        "type": "object",
        "x-kubernetes-preserve-unknown-fields": True,
        "x-kubernetes-embedded-resource": True,
    },
    ".Fields": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
    ".FieldsV1": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
    ".JSON": {"x-kubernetes-preserve-unknown-fields": True},
}


class ConversionError(Exception):
    """The document cannot produce a structural schema (recursive refs,
    missing definitions) — callers fall back to preserve-unknown."""


def definition_for_gvk(doc: dict, group: str, version: str, kind: str) -> str | None:
    """Find the swagger definition name carrying the matching
    ``x-kubernetes-group-version-kind`` extension."""
    for name, definition in (doc.get("definitions") or {}).items():
        for gvk in definition.get(GVK_EXT) or []:
            if (gvk.get("group", "") == group and gvk.get("version") == version
                    and gvk.get("kind") == kind):
                return name
    return None


class SwaggerConverter:
    """One conversion pass over a swagger document (stateful for cycle
    detection, like the reference's ``visited`` set)."""

    def __init__(self, doc: dict, root_name: str):
        self.definitions = doc.get("definitions") or {}
        self.root_name = root_name
        self._visiting: set[str] = set()

    def convert(self) -> dict:
        if self.root_name not in self.definitions:
            raise ConversionError(f"definition {self.root_name!r} not found")
        return self._node(self.definitions[self.root_name], at_root=True)

    # ------------------------------------------------------------- walk

    def _node(self, node: dict, inherited_desc: str = "", at_root: bool = False) -> dict:
        ref = node.get("$ref")
        if ref is not None:
            return self._ref(ref, inherited_desc or node.get("description", ""))

        out: dict[str, Any] = {}
        desc = inherited_desc or node.get("description", "")
        if desc:
            out["description"] = desc

        # int-or-string carried inline (CRD-derived documents — e.g. a
        # kcp serving its published CRDs as swagger — express it as the
        # extension, not as a known $ref): pass it through, or the
        # round-trip would degrade it to an arbitrary subtree
        if node.get(INT_OR_STRING):
            out[INT_OR_STRING] = True
            return out

        if "properties" in node:  # Kind
            out["type"] = "object"
            if node.get("required"):
                out["required"] = list(node["required"])
            props = {}
            for fname, fnode in node["properties"].items():
                if at_root and fname == "metadata":
                    # the reference collapses the root metadata subtree
                    props[fname] = {"type": "object"}
                else:
                    props[fname] = self._node(
                        fnode, inherited_desc=fnode.get("description", ""))
            out["properties"] = props
            self._copy_preserve_unknown(node, out)
            self._list_extensions(node, out)
            return out

        if "additionalProperties" in node and isinstance(
                node["additionalProperties"], dict):  # Map
            out["type"] = "object"
            out["additionalProperties"] = self._node(node["additionalProperties"])
            return out

        ntype = node.get("type")
        if ntype == "array":
            out["type"] = "array"
            items = node.get("items") or {}
            item_schema = self._node(items, inherited_desc=items.get("description", ""))
            self._array_extensions(node, items, out, item_schema)
            out["items"] = item_schema
            return out

        if ntype:  # Primitive (incl. propertyless objects)
            out["type"] = ntype
            if node.get("format"):
                out["format"] = node["format"]
            if node.get("enum"):
                out["enum"] = list(node["enum"])
            self._copy_preserve_unknown(node, out)
            return out

        # Arbitrary: no type, no properties, no ref. VisitArbitrary
        # (discovery.go:325-335) sets embedded-resource and copies
        # preserve-unknown only when the source extension exists —
        # but that exact shape is invalid under Kubernetes structural
        # rules (embedded-resource requires preserve-unknown or
        # properties) and fails the reference's own schemacompat type
        # dispatch. Deliberate deviation: preserve-unknown defaults to
        # true when the source carries no extension.
        out["x-kubernetes-embedded-resource"] = True
        out[PRESERVE_UNKNOWN] = bool(node.get(PRESERVE_UNKNOWN, True))
        return out

    @staticmethod
    def _copy_preserve_unknown(node: dict, out: dict) -> None:
        if node.get(PRESERVE_UNKNOWN) is not None:
            out[PRESERVE_UNKNOWN] = bool(node[PRESERVE_UNKNOWN])

    def _ref(self, ref: str, inherited_desc: str) -> dict:
        name = ref[len(REF_PREFIX):] if ref.startswith(REF_PREFIX) else ref
        for suffix, known in KNOWN_REF_SCHEMAS.items():
            if name.endswith(suffix):
                out = copy.deepcopy(known)
                if inherited_desc:
                    out["description"] = inherited_desc
                return out
        if name in self._visiting:
            raise ConversionError(f"recursive schema not supported: {name}")
        target = self.definitions.get(name)
        if target is None:
            raise ConversionError(f"unresolved $ref: {name}")
        self._visiting.add(name)
        try:
            return self._node(target, inherited_desc=inherited_desc)
        finally:
            self._visiting.discard(name)

    # ------------------------------------------------------- extensions

    @staticmethod
    def _list_extensions(node: dict, out: dict) -> None:
        """Kind-level merge extensions (discovery.go:429-439)."""
        if node.get("x-kubernetes-patch-merge-key"):
            out["x-kubernetes-list-map-keys"] = [node["x-kubernetes-patch-merge-key"]]
        if node.get("x-kubernetes-list-map-keys"):
            out["x-kubernetes-list-map-keys"] = list(node["x-kubernetes-list-map-keys"])
        if node.get("x-kubernetes-list-type"):
            out["x-kubernetes-list-type"] = node["x-kubernetes-list-type"]

    def _array_extensions(self, node: dict, items: dict, out: dict,
                          item_schema: dict) -> None:
        """Array merge-strategy extensions -> list-type/list-map-keys
        (discovery.go:336-395)."""
        item_is_kind = "properties" in items or (
            "$ref" in items
            and "properties" in (self.definitions.get(
                items["$ref"][len(REF_PREFIX):], {}))
        )
        if node.get("x-kubernetes-list-type"):
            out["x-kubernetes-list-type"] = node["x-kubernetes-list-type"]
        elif node.get("x-kubernetes-patch-strategy"):
            strategy = node["x-kubernetes-patch-strategy"]
            parts = strategy.split(",")
            if "merge" in parts:
                out["x-kubernetes-list-type"] = "map" if item_is_kind else "set"
            else:
                out["x-kubernetes-list-type"] = "atomic"
        if node.get("x-kubernetes-list-map-keys"):
            out["x-kubernetes-list-map-keys"] = list(node["x-kubernetes-list-map-keys"])
        elif node.get("x-kubernetes-patch-merge-key"):
            out["x-kubernetes-list-map-keys"] = [node["x-kubernetes-patch-merge-key"]]
            if not node.get("x-kubernetes-patch-strategy"):
                out["x-kubernetes-list-type"] = "map"
        # a map-typed list requires its map keys on the items
        # (discovery.go:381-391), unless a key field carries a default
        if out.get("x-kubernetes-list-map-keys") and item_schema.get("properties"):
            required = set(item_schema.get("required") or [])
            required.update(out["x-kubernetes-list-map-keys"])
            for fname, fschema in item_schema["properties"].items():
                if "default" in fschema:
                    required.discard(fname)
            item_schema["required"] = sorted(required)


def convert_definition(doc: dict, def_name: str) -> dict:
    """Convert one swagger definition to a structural CRD schema.

    Raises :class:`ConversionError` on recursion/missing refs — the
    caller's fallback chain (known schemas, preserve-unknown) applies.
    """
    return SwaggerConverter(doc, def_name).convert()


def doc_from_crds(crds: list[dict]) -> dict:
    """Synthesize an ``/openapi/v2`` document from CRD objects, one
    definition per served version, each carrying the GVK extension that
    :func:`definition_for_gvk` keys on. Used by both the REST handler
    and the in-process client so the puller sees the same document over
    either transport (reference analog: the apiserver's served openapi
    aggregate, consumed at discovery.go:60-66)."""
    definitions: dict[str, dict] = {}
    for crd in crds:
        spec = crd.get("spec") or {}
        group = spec.get("group", "")
        kind = (spec.get("names") or {}).get("kind", "")
        for v in spec.get("versions", []):
            schema = (v.get("schema") or {}).get("openAPIV3Schema")
            if not schema or not kind:
                continue
            d = copy.deepcopy(schema)
            d[GVK_EXT] = [{"group": group, "version": v.get("name", ""),
                           "kind": kind}]
            definitions[f"{group}.{v.get('name', '')}.{kind}"] = d
    return {"swagger": "2.0",
            "info": {"title": "kcp-tpu", "version": "v0.1.0"},
            "definitions": definitions}
