"""kcp-lint: contract-aware static analysis + the runtime sanitizer.

Static side (``scripts/lint.py`` / :mod:`.runner`): one AST checker per
cross-layer contract — CoW snapshot mutation, frozen encode-once bytes,
async/blocking discipline, lock-order acyclicity, the KCP_FAULTS point
registry, and metrics/docs drift — with per-line
``kcp-lint: disable=<rule> -- <justification>`` comment waivers.

Runtime side (:mod:`.sanitize`, ``KCP_SANITIZE=1``): the two data
contracts crash loudly instead of corrupting silently — store snapshots
freeze, cached bytes verify on every hit, and a lock tracker asserts the
same acquisition-order acyclicity the static pass checks.
"""

from .base import Finding  # noqa: F401
from .runner import RULES, LintReport, run_lint  # noqa: F401
