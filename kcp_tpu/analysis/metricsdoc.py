"""metrics-doc-drift: code-registered metrics <-> docs/operations.md.

Operators alert on what the runbook documents; a metric registered in
code but absent from docs/operations.md is invisible telemetry, and a
documented metric nothing registers is a runbook that lies. This checker
extracts every ``REGISTRY.counter/gauge/histogram`` registration (literal
names exactly; f-string names as globs, e.g. ``fused_{name}_seconds`` ->
``fused_*_seconds``) plus ``span("x")`` sites (which register
``x_seconds``), and reconciles both directions against the backticked
tokens of docs/operations.md — ``<name>``/``*`` in doc tokens match glob
segments, so ``workqueue_depth_<name>`` documents the
``workqueue_depth_{queue}`` family.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re

from .base import Finding, RepoChecker, SourceFile, attr_chain

DOCS_REL = os.path.join("docs", "operations.md")

#: a doc token with one of these suffixes claims to be a metric name
METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_size", "_depth",
                   "_rows", "_buckets", "_segments")

REGISTRY_METHODS = frozenset({"counter", "gauge", "histogram"})


def _name_args(node: ast.expr) -> tuple[list[str], list[str]]:
    """(literals, globs) a metric-name argument can evaluate to —
    conditional expressions contribute every literal branch."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value], []
    if isinstance(node, ast.IfExp):
        lit_a, glob_a = _name_args(node.body)
        lit_b, glob_b = _name_args(node.orelse)
        return lit_a + lit_b, glob_a + glob_b
    if isinstance(node, ast.JoinedStr):
        parts: list[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return [], ["".join(parts)]
    return [], []


def collect_code_metrics(files: list[SourceFile]
                         ) -> tuple[dict[str, tuple[str, int]],
                                    dict[str, tuple[str, int]]]:
    """(literal name -> site, glob -> site) across the file set."""
    literals: dict[str, tuple[str, int]] = {}
    globs: dict[str, tuple[str, int]] = {}
    for f in files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in REGISTRY_METHODS:
                recv = attr_chain(fn.value).lower()
                if not recv.endswith("registry"):
                    continue
                lits, gls = _name_args(node.args[0])
            elif isinstance(fn, ast.Name) and fn.id == "span":
                lits, gls = _name_args(node.args[0])
                lits = [s + "_seconds" for s in lits]
                gls = [g + "_seconds" for g in gls]
            else:
                continue
            for lit in lits:
                literals.setdefault(lit, (f.path, node.lineno))
            for glob in gls:
                if glob != "*":
                    globs.setdefault(glob, (f.path, node.lineno))
    return literals, globs


def collect_doc_tokens(docs_path: str) -> dict[str, int]:
    """Backticked identifier-ish tokens -> first line number."""
    tokens: dict[str, int] = {}
    try:
        with open(docs_path, encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return tokens
    for lineno, line in enumerate(lines, start=1):
        for span_text in re.findall(r"`([^`]+)`", line):
            # only whole-span tokens count as metric claims: a token
            # buried in a path/expression (`ops/foo.max_block_rows`,
            # `queues × queue_depth`) is prose, not a metric name
            tok = span_text.strip()
            if re.fullmatch(r"[a-z][a-z0-9_<>*]+", tok) and "_" in tok:
                tokens.setdefault(tok, lineno)
    return tokens


def _doc_token_concrete(tok: str) -> str:
    """A doc token with placeholders, concretized for glob matching:
    ``workqueue_depth_<name>`` -> ``workqueue_depth_x``."""
    return re.sub(r"(<[^>]*>|\*)", "x", tok)


class MetricsDocChecker(RepoChecker):
    name = "metrics-doc-drift"

    def check_repo(self, files: list[SourceFile],
                   repo_root: str) -> list[Finding]:
        findings: list[Finding] = []
        literals, globs = collect_code_metrics(files)
        docs_path = os.path.join(repo_root, DOCS_REL)
        tokens = collect_doc_tokens(docs_path)
        if not tokens and not literals:
            return findings
        concrete = {t: _doc_token_concrete(t) for t in tokens}

        # code -> docs: every registered metric is documented
        for name, (path, line) in sorted(literals.items()):
            if name not in tokens:
                findings.append(Finding(
                    self.name, path, line,
                    f"metric {name!r} is registered here but absent from "
                    f"{DOCS_REL} — document it (observability table or "
                    f"runbook)"))
        for glob, (path, line) in sorted(globs.items()):
            if not any(fnmatch.fnmatchcase(c, glob)
                       for c in concrete.values()):
                findings.append(Finding(
                    self.name, path, line,
                    f"dynamic metric family {glob!r} is registered here "
                    f"but no token in {DOCS_REL} documents it (use a "
                    f"<name> placeholder form)"))

        # docs -> code: every metric-looking doc token is registered
        for tok, lineno in sorted(tokens.items()):
            plain = "<" not in tok and "*" not in tok
            if plain and not tok.endswith(METRIC_SUFFIXES) \
                    and tok not in literals:
                continue  # not claiming to be a metric
            if plain and tok in literals:
                continue
            c = concrete[tok]
            if any(fnmatch.fnmatchcase(c, g) for g in globs):
                continue
            if not plain:
                # placeholder token: may also summarize several literals
                pat = fnmatch.translate(_placeholder_glob(tok))
                if any(re.fullmatch(pat, lit) for lit in literals):
                    continue
            if plain and any(fnmatch.fnmatchcase(tok, g) for g in globs):
                continue
            findings.append(Finding(
                self.name, DOCS_REL, lineno,
                f"docs/operations.md documents metric {tok!r} but nothing "
                f"in the codebase registers it — stale docs or a renamed "
                f"metric"))
        return findings


def _placeholder_glob(tok: str) -> str:
    return re.sub(r"(<[^>]*>|\*)", "*", tok)
