"""metrics-doc-drift: code-registered metrics <-> docs/operations.md.

Operators alert on what the runbook documents; a metric registered in
code but absent from docs/operations.md is invisible telemetry, and a
documented metric nothing registers is a runbook that lies. This checker
extracts every ``REGISTRY.counter/gauge/histogram`` registration (literal
names exactly; f-string names as globs, e.g. ``fused_{name}_seconds`` ->
``fused_*_seconds``) plus ``span("x")`` sites (which register
``x_seconds``), and reconciles both directions against the backticked
tokens of docs/operations.md — ``<name>``/``*`` in doc tokens match glob
segments, so ``workqueue_depth_<name>`` documents the
``workqueue_depth_{queue}`` family.

Trace spans get the same discipline (PR 12): every literal name at an
``obs.span(...)`` / ``obs.record_span(...)`` call site, and every phase
literal at an ``obs.phase(...)`` site (which records ``conv.<phase>``),
must appear as a backticked token inside the trace-span table region of
docs/operations.md (delimited by ``<!-- trace-spans:begin -->`` /
``<!-- trace-spans:end -->``), and every dotted token in that region
must be emitted by code — both directions, so the phase table an
operator reads while chasing a convergence regression can never drift
from what the tracer actually records.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re

from .base import Finding, RepoChecker, SourceFile, attr_chain

DOCS_REL = os.path.join("docs", "operations.md")

#: a doc token with one of these suffixes claims to be a metric name
METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_size", "_depth",
                   "_rows", "_buckets", "_segments")

REGISTRY_METHODS = frozenset({"counter", "gauge", "histogram"})


def _name_args(node: ast.expr) -> tuple[list[str], list[str]]:
    """(literals, globs) a metric-name argument can evaluate to —
    conditional expressions contribute every literal branch."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value], []
    if isinstance(node, ast.IfExp):
        lit_a, glob_a = _name_args(node.body)
        lit_b, glob_b = _name_args(node.orelse)
        return lit_a + lit_b, glob_a + glob_b
    if isinstance(node, ast.JoinedStr):
        parts: list[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return [], ["".join(parts)]
    return [], []


def collect_code_metrics(files: list[SourceFile]
                         ) -> tuple[dict[str, tuple[str, int]],
                                    dict[str, tuple[str, int]]]:
    """(literal name -> site, glob -> site) across the file set."""
    literals: dict[str, tuple[str, int]] = {}
    globs: dict[str, tuple[str, int]] = {}
    for f in files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in REGISTRY_METHODS:
                recv = attr_chain(fn.value).lower()
                if not recv.endswith("registry"):
                    continue
                lits, gls = _name_args(node.args[0])
            elif isinstance(fn, ast.Name) and fn.id == "span":
                lits, gls = _name_args(node.args[0])
                lits = [s + "_seconds" for s in lits]
                gls = [g + "_seconds" for g in gls]
            else:
                continue
            for lit in lits:
                literals.setdefault(lit, (f.path, node.lineno))
            for glob in gls:
                if glob != "*":
                    globs.setdefault(glob, (f.path, node.lineno))
    return literals, globs


def collect_doc_tokens(docs_path: str) -> dict[str, int]:
    """Backticked identifier-ish tokens -> first line number."""
    tokens: dict[str, int] = {}
    try:
        with open(docs_path, encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return tokens
    for lineno, line in enumerate(lines, start=1):
        for span_text in re.findall(r"`([^`]+)`", line):
            # only whole-span tokens count as metric claims: a token
            # buried in a path/expression (`ops/foo.max_block_rows`,
            # `queues × queue_depth`) is prose, not a metric name
            tok = span_text.strip()
            if re.fullmatch(r"[a-z][a-z0-9_<>*]+", tok) and "_" in tok:
                tokens.setdefault(tok, lineno)
    return tokens


SPAN_BEGIN = "<!-- trace-spans:begin -->"
SPAN_END = "<!-- trace-spans:end -->"

#: obs call sites whose first literal argument names a span (phase
#: literals record as ``conv.<phase>``)
SPAN_METHODS = frozenset({"span", "record_span"})


def collect_code_spans(files: list[SourceFile]) -> dict[str, tuple[str, int]]:
    """Span name -> first call site, from literal ``obs.span``/
    ``obs.record_span``/``obs.phase`` arguments across the file set."""
    spans: dict[str, tuple[str, int]] = {}
    for f in files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr not in SPAN_METHODS and fn.attr != "phase":
                continue
            recv = attr_chain(fn.value)
            if not recv.endswith("obs"):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            name = arg.value if fn.attr != "phase" else "conv." + arg.value
            spans.setdefault(name, (f.path, node.lineno))
    return spans


def collect_doc_spans(docs_path: str) -> dict[str, int]:
    """Backticked dotted tokens inside the trace-span table region."""
    tokens: dict[str, int] = {}
    try:
        with open(docs_path, encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return tokens
    inside = False
    for lineno, line in enumerate(lines, start=1):
        if SPAN_BEGIN in line:
            inside = True
            continue
        if SPAN_END in line:
            inside = False
            continue
        if not inside:
            continue
        for span_text in re.findall(r"`([^`]+)`", line):
            tok = span_text.strip()
            if re.fullmatch(r"[a-z][a-z0-9_]*(\.[a-z0-9_<>]+)+", tok):
                tokens.setdefault(tok, lineno)
    return tokens


def _doc_token_concrete(tok: str) -> str:
    """A doc token with placeholders, concretized for glob matching:
    ``workqueue_depth_<name>`` -> ``workqueue_depth_x``."""
    return re.sub(r"(<[^>]*>|\*)", "x", tok)


class MetricsDocChecker(RepoChecker):
    name = "metrics-doc-drift"

    def check_repo(self, files: list[SourceFile],
                   repo_root: str) -> list[Finding]:
        findings: list[Finding] = []
        literals, globs = collect_code_metrics(files)
        docs_path = os.path.join(repo_root, DOCS_REL)
        tokens = collect_doc_tokens(docs_path)
        if not tokens and not literals:
            return self._check_spans(files, docs_path)
        concrete = {t: _doc_token_concrete(t) for t in tokens}

        # code -> docs: every registered metric is documented
        for name, (path, line) in sorted(literals.items()):
            if name not in tokens:
                findings.append(Finding(
                    self.name, path, line,
                    f"metric {name!r} is registered here but absent from "
                    f"{DOCS_REL} — document it (observability table or "
                    f"runbook)"))
        for glob, (path, line) in sorted(globs.items()):
            if not any(fnmatch.fnmatchcase(c, glob)
                       for c in concrete.values()):
                findings.append(Finding(
                    self.name, path, line,
                    f"dynamic metric family {glob!r} is registered here "
                    f"but no token in {DOCS_REL} documents it (use a "
                    f"<name> placeholder form)"))

        # docs -> code: every metric-looking doc token is registered
        for tok, lineno in sorted(tokens.items()):
            plain = "<" not in tok and "*" not in tok
            if plain and not tok.endswith(METRIC_SUFFIXES) \
                    and tok not in literals:
                continue  # not claiming to be a metric
            if plain and tok in literals:
                continue
            c = concrete[tok]
            if any(fnmatch.fnmatchcase(c, g) for g in globs):
                continue
            if not plain:
                # placeholder token: may also summarize several literals
                pat = fnmatch.translate(_placeholder_glob(tok))
                if any(re.fullmatch(pat, lit) for lit in literals):
                    continue
            if plain and any(fnmatch.fnmatchcase(tok, g) for g in globs):
                continue
            findings.append(Finding(
                self.name, DOCS_REL, lineno,
                f"docs/operations.md documents metric {tok!r} but nothing "
                f"in the codebase registers it — stale docs or a renamed "
                f"metric"))

        findings.extend(self._check_spans(files, docs_path))
        return findings

    def _check_spans(self, files: list[SourceFile],
                     docs_path: str) -> list[Finding]:
        """Trace spans <-> the docs trace-span table, both directions."""
        findings: list[Finding] = []
        code_spans = collect_code_spans(files)
        doc_spans = collect_doc_spans(docs_path)
        for name, (path, line) in sorted(code_spans.items()):
            if name not in doc_spans:
                findings.append(Finding(
                    self.name, path, line,
                    f"trace span {name!r} is recorded here but absent "
                    f"from the trace-span table in {DOCS_REL} (between "
                    f"the trace-spans markers) — document it"))
        for tok, lineno in sorted(doc_spans.items()):
            if tok not in code_spans:
                findings.append(Finding(
                    self.name, DOCS_REL, lineno,
                    f"the trace-span table documents {tok!r} but no "
                    f"obs.span/obs.phase/obs.record_span call site "
                    f"records it — stale docs or a renamed span"))
        return findings


def _placeholder_glob(tok: str) -> str:
    return re.sub(r"(<[^>]*>|\*)", "*", tok)
