"""cow-mutation: flag in-place mutation of CoW store snapshots.

The PR 3 read-path contract (docs/operations.md "CoW contract"): with
``KCP_STORE_INDEX=1`` the store shares references between storage,
``list`` results, ``get_snapshot``, informer caches, watch ``Event``
payloads, and ``_sync_view_ro`` views. Mutating any of them corrupts the
store — silently, with no event and no RV bump — and with encode-once
serving on, also desynchronizes every cached byte string. This checker
taints values flowing out of the snapshot-returning APIs and flags
in-place writes to them; the fix is always the same: start from ``get``
(a private copy) or ``copy.deepcopy``, then write through ``update``.
"""

from __future__ import annotations

import ast

from .base import FileChecker, Finding, SourceFile, attr_chain
from .dataflow import COLL, ELEM, SAFE_CALLS, Taint, TaintScanner

#: helpers that mutate their first argument in place — passing a shared
#: snapshot into one is as much a violation as subscript assignment
ARG_MUTATORS = {
    "set_condition": 0,
    "remove_condition": 0,
    "set_ready": 0,
    "set_not_ready": 0,
    "set_synced_resources": 0,
    "accept_names": 0,
    "_stamp": 0,
}

#: functions that return a private deep copy of their input
COPYING_CALLS = SAFE_CALLS | {"transform_for_downstream"}


def _unwrap(node: ast.expr) -> ast.expr:
    while isinstance(node, ast.Await):
        node = node.value
    return node


def _effective_method(call: ast.Call) -> tuple[str, str]:
    """(method name, receiver chain) of a call, looking through the
    handler's ``self._st(self.store.list, ...)`` executor indirection."""
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    chain = attr_chain(fn)
    if name == "_st" and call.args and isinstance(call.args[0], ast.Attribute):
        inner = call.args[0]
        return inner.attr, attr_chain(inner)
    return name, chain


class CowScanner(TaintScanner):
    rule = "cow-mutation"
    arg_mutators = ARG_MUTATORS

    def describe_mutation(self, text: str) -> str:
        return (f"in-place mutation of CoW snapshot {text!r} "
                f"(shared with the store; re-get() or deepcopy first)")

    def taint_of_call(self, call: ast.Call, env: dict[str, Taint]) -> Taint:
        name, chain = _effective_method(call)
        if name in COPYING_CALLS:
            return None
        if name == "get_snapshot" or name == "_sync_view_ro":
            return ELEM
        if "informer" in chain:
            if name == "get":
                return ELEM
            if name in ("list", "index"):
                return COLL
        if isinstance(call.func, ast.Attribute):
            base = self.taint(call.func.value, env)
            if base == ELEM and name == "get":
                return ELEM  # dict.get on a snapshot shares nested values
            if base in (ELEM, COLL) and name in ("items", "values"):
                return COLL
        return None

    def taint_of_attribute(self, node: ast.Attribute,
                           env: dict[str, Taint]) -> Taint:
        if node.attr in ("object", "old_object"):
            return ELEM  # watch Event payloads share store snapshots
        if node.attr == "cache" and "informer" in attr_chain(node):
            return COLL
        return None

    def tuple_call_taints(self, call: ast.Call,
                          n_targets: int) -> list[Taint] | None:
        name, _chain = _effective_method(call)
        if name == "list" and n_targets == 2:
            # `(items, rv) = <store-or-client>.list(...)`: items share
            # storage references on indexed stores
            return [COLL, None]
        return None

    def taint(self, node: ast.AST, env: dict[str, Taint]) -> Taint:
        return super().taint(_unwrap(node) if isinstance(node, ast.expr)
                             else node, env)

    def _handle_assign(self, targets: list[ast.expr], value: ast.expr,
                       env: dict[str, Taint]) -> None:
        super()._handle_assign(targets, _unwrap(value), env)


class CowChecker(FileChecker):
    name = "cow-mutation"

    def check(self, f: SourceFile) -> list[Finding]:
        return CowScanner(f).run()
