"""Shared lint infrastructure: findings, waivers, checker protocol.

The analysis package is the ``go vet`` of this codebase: each checker
mechanically enforces one *cross-layer contract* that the runtime can
only catch after the damage is done (a mutated CoW snapshot corrupts
every informer cache sharing it; a blocking call inside ``async def``
stalls every watch stream on the loop). Checkers are pure-AST — no
imports of the checked code, no jax, safe to run anywhere python runs.

Waiver grammar (the only sanctioned way to silence a finding): append a
comment of the form ``kcp-lint: disable=cow-mutation -- <why this site
is a legitimate write boundary>`` to the flagged line. A waiver names
the rule(s) it silences and MUST carry a justification after ``--``; a
bare waiver is itself a finding (``waiver-syntax``) so exemptions stay
auditable. Waivers apply to findings anchored on their own line.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "Waiver",
    "FileChecker",
    "RepoChecker",
    "SourceFile",
    "parse_waivers",
    "attr_chain",
    "call_name",
    "WAIVER_RE",
]

WAIVER_RE = re.compile(
    r"#\s*kcp-lint:\s*disable=([a-z0-9_,\- ]+?)\s*(?:--\s*(.*))?$"
)

#: a line only *claims* to be a waiver when the comment marker and the
#: disable keyword are both present — prose merely mentioning the tool
#: (docstrings, the regex above) must not parse as a malformed waiver
_WAIVER_CLAIM_RE = re.compile(r"#\s*kcp-lint\b")


@dataclass
class Finding:
    """One contract violation at one source location."""

    rule: str
    path: str
    line: int
    message: str
    waived: bool = False
    justification: str = ""

    def to_dict(self) -> dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "waived": self.waived,
            "justification": self.justification,
        }

    def render(self) -> str:
        tag = " [waived]" if self.waived else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


@dataclass
class Waiver:
    line: int
    rules: frozenset[str]
    justification: str
    used: bool = False


@dataclass
class SourceFile:
    """A parsed python file: path (repo-relative), source, tree, waivers."""

    path: str
    source: str
    tree: ast.Module
    waivers: dict[int, Waiver] = field(default_factory=dict)

    @property
    def lines(self) -> list[str]:
        return self.source.splitlines()


def parse_waivers(source: str, path: str) -> tuple[dict[int, Waiver], list[Finding]]:
    """Extract per-line waivers; malformed ones become findings."""
    waivers: dict[int, Waiver] = {}
    findings: list[Finding] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        if "kcp-lint" not in line or _WAIVER_CLAIM_RE.search(line) is None:
            continue
        m = WAIVER_RE.search(line)
        if m is None:
            findings.append(Finding(
                "waiver-syntax", path, lineno,
                "malformed waiver comment (expected "
                "'kcp-lint: disable=<rule>[,<rule>] -- <justification>')"))
            continue
        rules = frozenset(
            r.strip() for r in m.group(1).split(",") if r.strip())
        justification = (m.group(2) or "").strip()
        if not rules:
            findings.append(Finding(
                "waiver-syntax", path, lineno,
                "waiver names no rules"))
            continue
        if not justification:
            findings.append(Finding(
                "waiver-syntax", path, lineno,
                "waiver has no justification (add '-- <why this site is "
                "a legitimate exemption>')"))
            continue
        waivers[lineno] = Waiver(lineno, rules, justification)
    return waivers, findings


def attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name chain: ``self.store.list`` ->
    "self.store.list"; non-name bases contribute ``?`` (calls,
    subscripts), so ``self.stores[i].list`` -> "?.list"."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def expr_text(node: ast.AST) -> str:
    """Human-readable source text of an expression for finding messages
    (matching logic keeps using :func:`attr_chain`)."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all exprs
        return attr_chain(node)


def call_name(call: ast.Call) -> str:
    """Terminal callable name: ``copy.deepcopy(x)`` -> "deepcopy",
    ``store.get_snapshot(...)`` -> "get_snapshot", ``open(...)`` ->
    "open"."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


class FileChecker:
    """A checker that inspects one file at a time."""

    name = "file-checker"

    def check(self, f: SourceFile) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


class RepoChecker:
    """A checker needing the whole file set (graphs, registries, docs)."""

    name = "repo-checker"

    def check_repo(self, files: list[SourceFile],
                   repo_root: str) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError
