"""Lint orchestration: file discovery, checker execution, waivers,
reporting. ``scripts/lint.py`` is the CLI face of this module."""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

from .asyncdiscipline import AsyncDisciplineChecker
from .base import FileChecker, Finding, RepoChecker, SourceFile, parse_waivers
from .cow import CowChecker
from .faultpoints import FaultPointChecker
from .frozenbytes import FrozenBytesChecker
from .lockorder import LockOrderChecker
from .metricsdoc import MetricsDocChecker

#: the linted surface: the package + the bench harness. Tests are
#: deliberately excluded — fixtures violate contracts on purpose — but
#: repo-level checkers still read tests/ for evidence (fault drills).
DEFAULT_TARGETS = ("kcp_tpu", "bench.py", "__graft_entry__.py")

ALL_CHECKERS: tuple[FileChecker | RepoChecker, ...] = (
    CowChecker(),
    FrozenBytesChecker(),
    AsyncDisciplineChecker(),
    LockOrderChecker(),
    FaultPointChecker(),
    MetricsDocChecker(),
)

RULES = tuple(c.name for c in ALL_CHECKERS) + ("waiver-syntax",)


@dataclass
class LintReport:
    findings: list[Finding] = field(default_factory=list)  # active
    waived: list[Finding] = field(default_factory=list)
    unused_waivers: list[tuple[str, int, str]] = field(default_factory=list)
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict[str, object]:
        by_rule: dict[str, int] = {}
        for fi in self.findings:
            by_rule[fi.rule] = by_rule.get(fi.rule, 0) + 1
        return {
            "ok": self.ok,
            "files_checked": self.files_checked,
            "findings": [fi.to_dict() for fi in self.findings],
            "waived": [fi.to_dict() for fi in self.waived],
            "unused_waivers": [
                {"path": p, "line": ln, "rules": r}
                for p, ln, r in self.unused_waivers],
            "summary": {
                "active": len(self.findings),
                "waived": len(self.waived),
                "by_rule": by_rule,
            },
        }

    def render(self) -> str:
        out: list[str] = []
        for fi in self.findings:
            out.append(fi.render())
        for fi in self.waived:
            out.append(fi.render())
        for path, line, rules in self.unused_waivers:
            out.append(f"{path}:{line}: unused waiver for {rules} "
                       f"(nothing to silence — remove it)")
        out.append(
            f"kcp-lint: {len(self.findings)} finding(s), "
            f"{len(self.waived)} waived, {self.files_checked} files")
        return "\n".join(out)


def discover(repo_root: str, targets: tuple[str, ...] = DEFAULT_TARGETS
             ) -> list[str]:
    paths: list[str] = []
    for target in targets:
        abs_t = os.path.join(repo_root, target)
        if os.path.isfile(abs_t):
            paths.append(target)
        elif os.path.isdir(abs_t):
            for dirpath, dirnames, filenames in os.walk(abs_t):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        paths.append(os.path.relpath(
                            os.path.join(dirpath, name), repo_root))
    return sorted(set(paths))


def load_files(repo_root: str, paths: list[str]
               ) -> tuple[list[SourceFile], list[Finding]]:
    files: list[SourceFile] = []
    findings: list[Finding] = []
    for rel in paths:
        try:
            with open(os.path.join(repo_root, rel), encoding="utf-8") as fh:
                src = fh.read()
        except OSError as err:
            findings.append(Finding("waiver-syntax", rel, 0,
                                    f"unreadable file: {err}"))
            continue
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as err:
            findings.append(Finding(
                "waiver-syntax", rel, err.lineno or 0,
                f"syntax error: {err.msg}"))
            continue
        waivers, wfindings = parse_waivers(src, rel)
        findings.extend(wfindings)
        files.append(SourceFile(rel, src, tree, waivers))
    return files, findings


def run_lint(repo_root: str,
             rules: tuple[str, ...] | None = None,
             targets: tuple[str, ...] = DEFAULT_TARGETS) -> LintReport:
    report = LintReport()
    files, raw = load_files(repo_root, discover(repo_root, targets))
    report.files_checked = len(files)
    by_path = {f.path: f for f in files}

    for checker in ALL_CHECKERS:
        if rules is not None and checker.name not in rules:
            continue
        if isinstance(checker, FileChecker):
            for f in files:
                raw.extend(checker.check(f))
        else:
            raw.extend(checker.check_repo(files, repo_root))

    for fi in raw:
        if rules is not None and fi.rule not in rules \
                and fi.rule != "waiver-syntax":
            continue
        f = by_path.get(fi.path)
        waiver = f.waivers.get(fi.line) if f is not None else None
        if waiver is not None and fi.rule in waiver.rules \
                and fi.rule != "waiver-syntax":
            waiver.used = True
            fi.waived = True
            fi.justification = waiver.justification
            report.waived.append(fi)
        else:
            report.findings.append(fi)

    for f in files:
        for waiver in f.waivers.values():
            if not waiver.used:
                report.unused_waivers.append(
                    (f.path, waiver.line, ",".join(sorted(waiver.rules))))

    report.findings.sort(key=lambda fi: (fi.path, fi.line, fi.rule))
    report.waived.sort(key=lambda fi: (fi.path, fi.line, fi.rule))
    return report


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="kcp-lint",
        description="contract-aware static analysis for kcp-tpu "
                    "(CoW snapshots, encode-once bytes, async/lock "
                    "discipline, fault points, metrics docs)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto from this file)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset "
                             f"(all: {', '.join(RULES)})")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("targets", nargs="*",
                        help=f"files/dirs to lint (default: "
                             f"{' '.join(DEFAULT_TARGETS)})")
    args = parser.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    rules = tuple(r.strip() for r in args.rules.split(",")) \
        if args.rules else None
    targets = tuple(args.targets) if args.targets else DEFAULT_TARGETS
    report = run_lint(root, rules=rules, targets=targets)
    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.render())
    return 0 if report.ok else 1
