"""Local (per-function) taint dataflow shared by the contract checkers.

``cow-mutation`` and ``frozen-bytes`` are the same analysis with
different sources and sinks: values flowing out of a known
*snapshot-returning* API are tainted, taint propagates through local
aliases / subscripts / loops, and a *mutation* of a tainted value is a
finding. The flow is deliberately function-local and forward-only —
single pass in source order, branches merged by union — which trades a
little recall for near-zero false positives on the shapes this codebase
actually writes (the waiver mechanism covers the true write boundaries).

Taint kinds:

- ``ELEM``: the value itself is a shared snapshot (mutating it corrupts
  the store / cache / every other reader),
- ``COLL``: a freshly-built container whose *elements* are shared
  (mutating the container is fine; mutating an element is not).
"""

from __future__ import annotations

import ast
from typing import Optional

from .base import Finding, SourceFile, expr_text

ELEM = "elem"
COLL = "coll"

Taint = Optional[str]

#: in-place mutators on dicts/lists/sets: calling one on a tainted value
#: is a mutation sink
MUTATOR_METHODS = frozenset({
    "setdefault", "update", "pop", "popitem", "clear",
    "append", "extend", "insert", "remove", "sort", "reverse",
    "add", "discard",
})

#: calls that return a private copy — taint does not flow through them
SAFE_CALLS = frozenset({"deepcopy", "copy"})


class TaintScanner:
    """One checker pass over one file. Subclasses define the sources
    (what taints) and refine the sinks (what counts as mutation)."""

    rule = "taint"
    #: function name -> index of the argument it mutates in place
    arg_mutators: dict[str, int] = {}
    #: flag ``name += ...`` on an ELEM-tainted bare name (bytes contract)
    flag_aug_name = False

    def __init__(self, f: SourceFile):
        self.f = f
        self.findings: list[Finding] = []

    # ------------------------------------------------------------- hooks

    def taint_of_call(self, call: ast.Call, env: dict[str, Taint]) -> Taint:
        """Taint of a call expression (source detection)."""
        return None

    def taint_of_attribute(self, node: ast.Attribute,
                           env: dict[str, Taint]) -> Taint:
        return None

    def tuple_call_taints(self, call: ast.Call,
                          n_targets: int) -> list[Taint] | None:
        """Taints for ``a, b = call(...)`` unpacking (source detection)."""
        return None

    def describe_mutation(self, text: str) -> str:
        return f"in-place mutation of shared value {text!r}"

    # -------------------------------------------------------------- run

    def run(self) -> list[Finding]:
        for fn in ast.walk(self.f.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_block(fn.body, {})
        self._scan_block(
            [s for s in self.f.tree.body
             if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))],
            {})
        return self.findings

    # ------------------------------------------------------- taint eval

    def taint(self, node: ast.AST, env: dict[str, Taint]) -> Taint:
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Call):
            return self.taint_of_call(node, env)
        if isinstance(node, ast.Attribute):
            return self.taint_of_attribute(node, env)
        if isinstance(node, ast.Subscript):
            base = self.taint(node.value, env)
            if base in (COLL, ELEM):
                return ELEM
            return None
        if isinstance(node, ast.BoolOp):
            ts = [self.taint(v, env) for v in node.values]
            if ELEM in ts:
                return ELEM
            if COLL in ts:
                return COLL
            return None
        if isinstance(node, ast.IfExp):
            ts = [self.taint(node.body, env), self.taint(node.orelse, env)]
            return ELEM if ELEM in ts else (COLL if COLL in ts else None)
        if isinstance(node, ast.NamedExpr):
            t = self.taint(node.value, env)
            env[node.target.id] = t
            return t
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            if len(node.generators) == 1:
                gen = node.generators[0]
                it = self.taint(gen.iter, env)
                inner = dict(env)
                if it in (COLL, ELEM):
                    for n in ast.walk(gen.target):
                        if isinstance(n, ast.Name):
                            inner[n.id] = ELEM
                t_elt = self.taint(node.elt, inner)
                return COLL if t_elt == ELEM else None
            return None
        if isinstance(node, ast.Starred):
            return self.taint(node.value, env)
        return None

    # --------------------------------------------------------- statements

    def _scan_block(self, stmts: list[ast.stmt], env: dict[str, Taint]) -> None:
        for st in stmts:
            self._scan_stmt(st, env)

    def _scan_stmt(self, st: ast.stmt, env: dict[str, Taint]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate scope, scanned on its own
        if isinstance(st, ast.Assign):
            self._handle_assign(st.targets, st.value, env)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            self._handle_assign([st.target], st.value, env)
        elif isinstance(st, ast.AugAssign):
            self._check_target_mutation(st.target, env, aug=True)
            self._scan_value(st.value, env)
        elif isinstance(st, ast.Expr):
            self._scan_value(st.value, env)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            it = self.taint(st.iter, env)
            self._scan_value(st.iter, env)
            if it in (COLL, ELEM):
                for n in ast.walk(st.target):
                    if isinstance(n, ast.Name):
                        env[n.id] = ELEM
            else:
                for n in ast.walk(st.target):
                    if isinstance(n, ast.Name):
                        env[n.id] = None
            self._scan_block(st.body, env)
            self._scan_block(st.orelse, env)
        elif isinstance(st, ast.While):
            self._scan_block(st.body, env)
            self._scan_block(st.orelse, env)
        elif isinstance(st, ast.If):
            self._scan_block(st.body, env)
            self._scan_block(st.orelse, env)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._scan_value(item.context_expr, env)
                if item.optional_vars is not None and isinstance(
                        item.optional_vars, ast.Name):
                    env[item.optional_vars.id] = self.taint(
                        item.context_expr, env)
            self._scan_block(st.body, env)
        elif isinstance(st, ast.Try):
            self._scan_block(st.body, env)
            for h in st.handlers:
                self._scan_block(h.body, env)
            self._scan_block(st.orelse, env)
            self._scan_block(st.finalbody, env)
        elif isinstance(st, ast.Delete):
            for tgt in st.targets:
                if isinstance(tgt, ast.Subscript):
                    if self.taint(tgt.value, env) == ELEM:
                        self._flag(tgt, f"del on shared value "
                                        f"{expr_text(tgt.value)!r}")
                elif isinstance(tgt, ast.Name):
                    env[tgt.id] = None
        elif isinstance(st, ast.Return) and st.value is not None:
            self._scan_value(st.value, env)

    def _handle_assign(self, targets: list[ast.expr], value: ast.expr,
                       env: dict[str, Taint]) -> None:
        self._scan_value(value, env)
        # tuple-unpack sources: `items, rv = store.list(...)`
        if (len(targets) == 1 and isinstance(targets[0], ast.Tuple)
                and isinstance(value, ast.Call)):
            elts = targets[0].elts
            taints = self.tuple_call_taints(value, len(elts))
            if taints is not None:
                for tgt, t in zip(elts, taints):
                    if isinstance(tgt, ast.Name):
                        env[tgt.id] = t
                return
        t = self.taint(value, env)
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                env[tgt.id] = t
            elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
                self._check_target_mutation(tgt, env)
            elif isinstance(tgt, ast.Tuple):
                for n in tgt.elts:
                    if isinstance(n, ast.Name):
                        env[n.id] = ELEM if t in (COLL, ELEM) else None

    def _check_target_mutation(self, tgt: ast.expr, env: dict[str, Taint],
                               aug: bool = False) -> None:
        if isinstance(tgt, (ast.Subscript, ast.Attribute)):
            if self.taint(tgt.value, env) == ELEM:
                self._flag(tgt, self.describe_mutation(expr_text(tgt.value)))
        elif isinstance(tgt, ast.Name):
            if aug and self.flag_aug_name and env.get(tgt.id) == ELEM:
                self._flag(tgt, self.describe_mutation(tgt.id))
            elif not aug:
                env[tgt.id] = None

    def _scan_value(self, node: ast.expr, env: dict[str, Taint]) -> None:
        """Mutation sinks inside an expression statement / value."""
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            if isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_METHODS:
                if self.taint(fn.value, env) == ELEM:
                    self._flag(call, self.describe_mutation(
                        expr_text(fn.value)) + f" via .{fn.attr}()")
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            idx = self.arg_mutators.get(name)
            if idx is not None and idx < len(call.args):
                if self.taint(call.args[idx], env) == ELEM:
                    self._flag(call, f"{name}() mutates its argument "
                                     f"{expr_text(call.args[idx])!r}, which "
                                     f"is a shared value")

    def _flag(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            self.rule, self.f.path, getattr(node, "lineno", 0), message))
