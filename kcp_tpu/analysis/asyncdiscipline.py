"""async-discipline: blocking calls in ``async def`` and awaits under
threading locks.

The serving loop is one asyncio thread shared by every request, watch
stream, controller tick, and health probe. A single ``time.sleep`` /
blocking ``open`` / synchronous socket call inside ``async def`` freezes
all of them for its duration — the PR 1 store-pool work exists exactly
because one blocking backend call stalled the world. The second shape is
the asyncio+thread hybrid deadlock: ``await`` while holding a
``threading.Lock`` parks the coroutine with the lock held; any *thread*
that then blocks on that lock can never be released by the loop it is
blocking.
"""

from __future__ import annotations

import ast

from .base import FileChecker, Finding, SourceFile, attr_chain

#: dotted-call chains that block the calling thread
BLOCKING_CHAINS = (
    "time.sleep",
    "socket.create_connection",
    "socket.socket",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.delete",
    "requests.request",
)

THREADING_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
                        "BoundedSemaphore")


def _collect_threading_locks(tree: ast.Module) -> set[str]:
    """Names/attrs assigned from ``threading.Lock()`` (or the sanitizer's
    ``make_lock(...)`` factory) anywhere in the module."""
    locks: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        chain = attr_chain(node.value.func)
        is_lock = (
            (chain.startswith("threading.")
             and chain.split(".")[-1] in THREADING_LOCK_CTORS)
            or chain.endswith("make_lock")
        )
        if not is_lock:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                locks.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                locks.add(tgt.attr)
    return locks


def _body_nodes(fn: ast.AsyncFunctionDef) -> list[ast.AST]:
    """All nodes lexically inside the async function, not descending into
    nested function/lambda scopes (those run elsewhere)."""
    out: list[ast.AST] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            out.append(child)
            walk(child)

    for st in fn.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        out.append(st)
        walk(st)
    return out


class AsyncDisciplineChecker(FileChecker):
    name = "async-discipline"

    def check(self, f: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        locks = _collect_threading_locks(f.tree)
        for fn in ast.walk(f.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            nodes = _body_nodes(fn)
            for node in nodes:
                if isinstance(node, ast.Call):
                    self._check_call(node, fn, f, findings)
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    self._check_with(node, fn, f, locks, findings)
        return findings

    def _check_call(self, call: ast.Call, fn: ast.AsyncFunctionDef,
                    f: SourceFile, findings: list[Finding]) -> None:
        chain = attr_chain(call.func)
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            findings.append(Finding(
                self.name, f.path, call.lineno,
                f"blocking file open() inside async def {fn.name!r} — "
                f"offload to a thread (run_in_executor) or open before "
                f"entering the loop"))
            return
        for blocked in BLOCKING_CHAINS:
            if chain == blocked or chain.endswith("." + blocked):
                findings.append(Finding(
                    self.name, f.path, call.lineno,
                    f"blocking call {chain}() inside async def "
                    f"{fn.name!r} stalls the whole serving loop — use the "
                    f"asyncio equivalent or run_in_executor"))
                return

    def _check_with(self, node: ast.With | ast.AsyncWith,
                    fn: ast.AsyncFunctionDef, f: SourceFile,
                    locks: set[str], findings: list[Finding]) -> None:
        held = []
        for item in node.items:
            expr = item.context_expr
            name = ""
            if isinstance(expr, ast.Attribute):
                name = expr.attr
            elif isinstance(expr, ast.Name):
                name = expr.id
            if name in locks:
                held.append(name)
        if not held:
            return
        for inner in ast.walk(node):
            if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(inner, ast.Await):
                findings.append(Finding(
                    self.name, f.path, inner.lineno,
                    f"await while holding threading lock "
                    f"{held[0]!r} in async def {fn.name!r} — the "
                    f"asyncio+thread hybrid deadlock shape (park the "
                    f"lock-protected work in a thread, or use an "
                    f"asyncio.Lock)"))
                return
