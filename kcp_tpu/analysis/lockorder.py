"""lock-order: extract the lock acquisition graph, fail on cycles.

The codebase mixes asyncio with real threads (store-I/O pool, applier
pool, REST clients, the profiler), synchronized by a handful of
``threading.Lock``s. Deadlock needs two locks taken in opposite orders
on two threads — a property no unit test reliably exercises. This
checker builds the static acquisition graph: a ``with lockA:`` body that
acquires (directly, or via a same-class method call one level deep)
``lockB`` adds edge A→B; any cycle in the union graph across the repo is
a potential deadlock and fails the lint. The runtime sanitizer
(``KCP_SANITIZE=1``) asserts the same acyclicity over *observed*
acquisition pairs, catching orders the static pass cannot see.

Lock identity: ``module.Class.attr`` for ``self.x = threading.Lock()``,
``module.name`` for module-level locks — and the literal name for locks
made through ``sanitize.make_lock("...")``, so static nodes line up with
the runtime tracker's.
"""

from __future__ import annotations

import ast
import os

from .base import Finding, RepoChecker, SourceFile, attr_chain
from .asyncdiscipline import THREADING_LOCK_CTORS


def _modname(path: str) -> str:
    return os.path.splitext(path)[0].replace("/", ".")


def _lock_ctor_name(value: ast.expr) -> str | None:
    """For ``threading.Lock()`` returns ""; for ``make_lock("x")``
    returns "x"; else None."""
    if not isinstance(value, ast.Call):
        return None
    chain = attr_chain(value.func)
    if (chain.startswith("threading.")
            and chain.split(".")[-1] in THREADING_LOCK_CTORS):
        return ""
    if chain.endswith("make_lock"):
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            return value.args[0].value
        return ""
    return None


class _ClassLocks:
    def __init__(self) -> None:
        self.attr_ids: dict[str, str] = {}  # attr -> lock node id


class LockOrderChecker(RepoChecker):
    name = "lock-order"

    def check_repo(self, files: list[SourceFile],
                   repo_root: str) -> list[Finding]:
        findings: list[Finding] = []
        edges: dict[str, dict[str, tuple[str, int]]] = {}
        # method -> set of lock ids it acquires anywhere (for one-level
        # call propagation inside a held region)
        method_locks: dict[tuple[str, str, str], set[str]] = {}
        per_class: dict[tuple[str, str], _ClassLocks] = {}

        for f in files:
            mod = _modname(f.path)
            for cls in [n for n in ast.walk(f.tree)
                        if isinstance(n, ast.ClassDef)]:
                cl = per_class.setdefault((mod, cls.name), _ClassLocks())
                for node in ast.walk(cls):
                    if not isinstance(node, ast.Assign):
                        continue
                    named = _lock_ctor_name(node.value)
                    if named is None:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            cl.attr_ids[tgt.attr] = (
                                named or f"{mod}.{cls.name}.{tgt.attr}")
            # module-level locks
            mod_locks: dict[str, str] = {}
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    named = _lock_ctor_name(node.value)
                    if named is None:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            mod_locks[tgt.id] = named or f"{mod}.{tgt.id}"
            f._mod_locks = mod_locks  # type: ignore[attr-defined]

        # pass 2: acquisition scan
        for f in files:
            mod = _modname(f.path)
            mod_locks = f._mod_locks  # type: ignore[attr-defined]
            for cls_name, fn in self._functions(f.tree):
                cl = per_class.get((mod, cls_name or ""), _ClassLocks())
                acquired: set[str] = set()
                self._scan(fn.body, [], cl, mod_locks, f, edges, acquired,
                           calls_out=[])
                method_locks[(mod, cls_name or "", fn.name)] = acquired

        # pass 3: one-level propagation through same-class calls made
        # while holding a lock
        for f in files:
            mod = _modname(f.path)
            mod_locks = f._mod_locks  # type: ignore[attr-defined]
            for cls_name, fn in self._functions(f.tree):
                cl = per_class.get((mod, cls_name or ""), _ClassLocks())
                calls_out: list[tuple[str, str, int]] = []
                self._scan(fn.body, [], cl, mod_locks, f, {}, set(),
                           calls_out=calls_out)
                for held, callee, lineno in calls_out:
                    for lock in method_locks.get((mod, cls_name or "", callee),
                                                 ()):
                        if lock != held:
                            edges.setdefault(held, {}).setdefault(
                                lock, (f.path, lineno))

        findings.extend(self._find_cycles(edges))
        return findings

    @staticmethod
    def _functions(tree: ast.Module
                   ) -> "list[tuple[str | None, ast.FunctionDef | ast.AsyncFunctionDef]]":
        out: list = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        out.append((node.name, sub))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((None, node))
        return out

    def _lock_id(self, expr: ast.expr, cl: _ClassLocks,
                 mod_locks: dict[str, str]) -> str | None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return cl.attr_ids.get(expr.attr)
        if isinstance(expr, ast.Name):
            return mod_locks.get(expr.id)
        return None

    def _scan(self, stmts: list, held: list[str], cl: _ClassLocks,
              mod_locks: dict[str, str], f: SourceFile,
              edges: dict[str, dict[str, tuple[str, int]]],
              acquired: set[str],
              calls_out: list[tuple[str, str, int]]) -> None:
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                new = []
                for item in st.items:
                    lock = self._lock_id(item.context_expr, cl, mod_locks)
                    if lock is not None:
                        acquired.add(lock)
                        for h in held:
                            if h != lock:
                                edges.setdefault(h, {}).setdefault(
                                    lock, (f.path, st.lineno))
                        new.append(lock)
                self._scan(st.body, held + new, cl, mod_locks, f, edges,
                           acquired, calls_out)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            else:
                # record same-class calls made while holding a lock
                if held:
                    for call in ast.walk(st):
                        if isinstance(call, ast.Call) and \
                                isinstance(call.func, ast.Attribute) and \
                                isinstance(call.func.value, ast.Name) and \
                                call.func.value.id == "self":
                            for h in held:
                                calls_out.append(
                                    (h, call.func.attr, call.lineno))
                for child_body in self._sub_bodies(st):
                    self._scan(child_body, held, cl, mod_locks, f, edges,
                               acquired, calls_out)

    @staticmethod
    def _sub_bodies(st: ast.stmt) -> "list[list[ast.stmt]]":
        out: list = []
        for attr in ("body", "orelse", "finalbody"):
            body = getattr(st, attr, None)
            if body and isinstance(body, list) and \
                    isinstance(body[0], ast.stmt):
                out.append(body)
        for h in getattr(st, "handlers", []) or []:
            out.append(h.body)
        return out

    def _find_cycles(self, edges: dict[str, dict[str, tuple[str, int]]]
                     ) -> list[Finding]:
        findings: list[Finding] = []
        WHITE, GREY, BLACK = 0, 1, 2
        color: dict[str, int] = {}
        stack: list[str] = []
        reported: set[frozenset[str]] = set()

        def visit(n: str) -> None:
            color[n] = GREY
            stack.append(n)
            for m in edges.get(n, {}):
                c = color.get(m, WHITE)
                if c == GREY:
                    cycle = stack[stack.index(m):] + [m]
                    key = frozenset(cycle)
                    if key not in reported:
                        reported.add(key)
                        path, line = edges[n][m]
                        findings.append(Finding(
                            self.name, path, line,
                            "lock acquisition cycle: "
                            + " -> ".join(cycle)
                            + " (two threads taking these in opposite "
                              "order deadlock)"))
                elif c == WHITE:
                    visit(m)
            stack.pop()
            color[n] = BLACK

        for n in list(edges):
            if color.get(n, WHITE) == WHITE:
                visit(n)
        return findings
