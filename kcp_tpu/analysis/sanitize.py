"""Runtime sanitizer (``KCP_SANITIZE=1``): the data contracts crash
loudly at the violating line instead of corrupting silently.

Three enforcement surfaces, all off (near-zero cost: one module-attr
read per site) unless enabled:

- **CoW snapshots freeze**: every object the store commits is deep-
  converted to :class:`FrozenDict`/:class:`FrozenList` proxies whose
  mutators raise :class:`ContractViolation` naming the contract. Since
  ``list`` results, informer caches, and watch ``Event`` payloads all
  share the stored snapshot, ANY in-place mutation by any consumer
  raises at the mutation site with a full traceback. ``get()`` (and any
  ``copy.deepcopy``) still hands back a plain, mutable copy — the
  sanctioned edit path is unchanged.
- **Frozen bytes verify on hit**: the encode-once caches re-encode on
  every cache hit and compare against the cached bytes; a scribbled or
  stale entry raises instead of serving corrupt bytes to every watcher.
  (Python ``bytes`` are immutable, so the attack surface is the cache
  *slots* — an overwritten ``_enc_line`` or ``_enc_bytes`` entry.)
- **Lock-order tracking**: locks built through :func:`make_lock` record
  held->acquired pairs per thread into one global digraph and assert the
  same acyclicity the static ``lock-order`` checker proves — but over
  *observed* orders, including cross-module ones the AST cannot see. A
  cycle raises at the second lock's acquire, before it can deadlock.

Enable with ``KCP_SANITIZE=1`` (read once), or programmatically via
:func:`enable` in tests. ``scripts/ci.sh`` runs the tier-1 differential
fuzzes under it.
"""

from __future__ import annotations

import copy as _copy
import os
import threading
from typing import Any, Iterable

__all__ = [
    "ContractViolation",
    "FrozenDict",
    "FrozenList",
    "enabled",
    "enable",
    "freeze",
    "thaw",
    "make_lock",
    "TrackedLock",
    "lock_edges",
    "reset_lock_tracking",
]


class ContractViolation(AssertionError):
    """A sanitizer-detected violation of a cross-layer contract. The
    message names the contract and the sanctioned alternative."""

    def __init__(self, contract: str, message: str):
        super().__init__(f"[{contract}] {message}")
        self.contract = contract


_ENABLED: bool | None = None


def enabled() -> bool:
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("KCP_SANITIZE", "").lower() in (
            "1", "true", "on")
    return _ENABLED


def enable(on: bool = True) -> None:
    """Programmatic toggle (tests, chaos harnesses)."""
    global _ENABLED
    _ENABLED = bool(on)


# ---------------------------------------------------------------------------
# CoW snapshot freeze proxies
# ---------------------------------------------------------------------------

_COW_MSG = (
    "CoW snapshot mutated in place — list results, informer caches and "
    "watch Event payloads share references with storage "
    "(docs/operations.md 'CoW contract'); re-get() or copy.deepcopy "
    "before editing, then write through update()"
)


def _raise_cow(*_args: Any, **_kwargs: Any) -> Any:
    raise ContractViolation("cow-mutation", _COW_MSG)


class FrozenDict(dict):
    """A dict whose mutators raise; deep copies thaw to plain dicts so
    the sanctioned edit path (``get`` -> mutate -> ``update``) still
    hands out mutable objects."""

    __setitem__ = _raise_cow
    __delitem__ = _raise_cow
    setdefault = _raise_cow
    update = _raise_cow
    pop = _raise_cow
    popitem = _raise_cow
    clear = _raise_cow
    __ior__ = _raise_cow

    def __deepcopy__(self, memo: dict) -> dict:
        return {k: _copy.deepcopy(v, memo) for k, v in self.items()}

    def __reduce__(self):
        return (dict, (dict(self),))


class FrozenList(list):
    __setitem__ = _raise_cow
    __delitem__ = _raise_cow
    append = _raise_cow
    extend = _raise_cow
    insert = _raise_cow
    remove = _raise_cow
    pop = _raise_cow
    clear = _raise_cow
    sort = _raise_cow
    reverse = _raise_cow
    __iadd__ = _raise_cow
    __imul__ = _raise_cow

    def __deepcopy__(self, memo: dict) -> list:
        return [_copy.deepcopy(v, memo) for v in self]

    def __reduce__(self):
        return (list, (list(self),))


def freeze(obj: Any) -> Any:
    """Deep-convert dicts/lists to frozen proxies (scalars unchanged)."""
    if type(obj) is dict or type(obj) is FrozenDict:
        return FrozenDict((k, freeze(v)) for k, v in obj.items())
    if type(obj) is list or type(obj) is FrozenList:
        return FrozenList(freeze(v) for v in obj)
    return obj


def thaw(obj: Any) -> Any:
    """Deep-convert frozen proxies back to plain containers."""
    if isinstance(obj, dict):
        return {k: thaw(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [thaw(v) for v in obj]
    return obj


# ---------------------------------------------------------------------------
# Lock-order tracking
# ---------------------------------------------------------------------------

class _HeldStacks(threading.local):
    def __init__(self) -> None:
        self.stack: list[str] = []


_HELD = _HeldStacks()
_GRAPH_LOCK = threading.Lock()  # guards the edge graph only, never user code
_EDGES: dict[str, set[str]] = {}


def lock_edges() -> dict[str, set[str]]:
    """Snapshot of the observed acquisition graph (tests/debugging)."""
    with _GRAPH_LOCK:
        return {k: set(v) for k, v in _EDGES.items()}


def reset_lock_tracking() -> None:
    with _GRAPH_LOCK:
        _EDGES.clear()
    _HELD.stack.clear()


def _path_exists(src: str, dst: str) -> list[str] | None:
    """DFS path src -> dst in the edge graph (caller holds _GRAPH_LOCK)."""
    seen = {src}
    stack: list[tuple[str, list[str]]] = [(src, [src])]
    while stack:
        node, path = stack.pop()
        for nxt in _EDGES.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class TrackedLock:
    """A ``threading.Lock`` recording held->acquired pairs; acquiring in
    an order that closes a cycle in the global graph raises BEFORE the
    deadlock can happen."""

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Lock()

    def _check_order(self) -> None:
        held = _HELD.stack
        if not held:
            return
        with _GRAPH_LOCK:
            for h in held:
                if h == self.name:
                    continue
                outs = _EDGES.setdefault(h, set())
                if self.name in outs:
                    continue
                path = _path_exists(self.name, h)
                if path is not None:
                    raise ContractViolation(
                        "lock-order",
                        f"acquiring {self.name!r} while holding {h!r} "
                        f"inverts the established order "
                        f"{' -> '.join(path)} — two threads taking these "
                        f"in opposite orders deadlock; acquire locks in "
                        f"one global order")
                outs.add(self.name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _HELD.stack.append(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        stack = _HELD.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


def make_lock(name: str) -> "threading.Lock | TrackedLock":
    """The lock factory for kcp_tpu locks: a plain ``threading.Lock``
    normally, a :class:`TrackedLock` under the sanitizer. The ``name``
    doubles as the lock's node id in both the runtime graph and the
    static ``lock-order`` checker, so the two passes agree."""
    if enabled():
        return TrackedLock(name)
    return threading.Lock()


# ---------------------------------------------------------------------------
# Frozen-bytes verification helpers (called from the encode caches)
# ---------------------------------------------------------------------------

def verify_bytes(cached: bytes, fresh: bytes, what: str) -> None:
    """Raise if a cached encoding no longer matches a fresh encode of
    its source snapshot — someone scribbled on the cache slot or mutated
    the snapshot behind the cache's back."""
    if cached != fresh:
        raise ContractViolation(
            "frozen-bytes",
            f"cached {what} diverged from a fresh encode "
            f"({len(cached)}B cached vs {len(fresh)}B fresh) — a cache "
            f"slot was overwritten or its snapshot mutated; cached bytes "
            f"are frozen shared state")


def freeze_iter(items: Iterable[Any]) -> list[Any]:
    """Freeze each element of an iterable (test helper)."""
    return [freeze(x) for x in items]
