"""frozen-bytes: flag writes / re-encodes of encode-once cache bytes.

The PR 5 serving contract (docs/architecture.md "Encode-once serving"):
bytes flowing out of ``encode_obj`` / ``encode_many`` / ``encode_event``
/ ``encode_events`` / ``list_encoded`` and the RV-keyed body cache are
*shared* — the same object is spliced into every response and every
watcher's stream. Treating them as scratch (``bytearray()`` wrapping,
element assignment, ``+=``) or round-tripping them back through
``json.loads`` on a serving path defeats the cache and, for mutable
wrappers, risks corrupting bytes mid-flight for every other consumer.
"""

from __future__ import annotations

import ast

from .base import FileChecker, Finding, SourceFile, expr_text
from .dataflow import COLL, ELEM, Taint, TaintScanner

ENCODE_ELEM = frozenset({"encode_obj", "encode_event"})
ENCODE_COLL = frozenset({"encode_many", "encode_events"})
CACHE_ATTRS = frozenset({"_enc_bytes", "_span_cache", "_list_cache"})


class FrozenBytesScanner(TaintScanner):
    rule = "frozen-bytes"
    flag_aug_name = True

    def describe_mutation(self, text: str) -> str:
        return (f"write to shared encode-once bytes {text!r} "
                f"(cached bytes are spliced into every response — "
                f"build new bytes instead)")

    def taint_of_call(self, call: ast.Call, env: dict[str, Taint]) -> Taint:
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name in ENCODE_ELEM:
            return ELEM
        if name in ENCODE_COLL:
            return COLL
        if name == "get" and isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Attribute) and \
                fn.value.attr in CACHE_ATTRS:
            return ELEM  # cache entry tuple: ent[1] is the shared bytes
        return None

    def taint_of_attribute(self, node: ast.Attribute,
                           env: dict[str, Taint]) -> Taint:
        if node.attr in CACHE_ATTRS:
            return COLL
        return None

    def tuple_call_taints(self, call: ast.Call,
                          n_targets: int) -> list[Taint] | None:
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else ""
        if name == "list_encoded" and n_targets == 2:
            return [COLL, None]
        return None

    def _scan_value(self, node: ast.expr, env: dict[str, Taint]) -> None:
        super()._scan_value(node, env)
        for call in ast.walk(node):
            if not isinstance(call, ast.Call) or not call.args:
                continue
            fn = call.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            arg0 = call.args[0]
            if self.taint(arg0, env) != ELEM:
                continue
            if name == "bytearray":
                self._flag(call, f"bytearray() wrap of shared encode-once "
                                 f"bytes {expr_text(arg0)!r} — treating "
                                 f"cached bytes as mutable scratch breaks "
                                 f"the frozen-bytes contract")
            elif name == "loads":
                self._flag(call, f"re-decoding shared encode-once bytes "
                                 f"{expr_text(arg0)!r} on a serving path — "
                                 f"splice the cached bytes instead of "
                                 f"round-tripping them through json")


class FrozenBytesChecker(FileChecker):
    name = "frozen-bytes"

    def check(self, f: SourceFile) -> list[Finding]:
        return FrozenBytesScanner(f).run()
