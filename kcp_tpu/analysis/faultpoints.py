"""fault-point-registry: every KCP_FAULTS point is declared, spelled
identically at every site, and exercised by at least one test.

The fault framework (kcp_tpu/faults.py) is string-keyed: a typo'd point
name at an injection site silently never fires, and a chaos schedule
naming a point nothing injects is a test asserting nothing. The registry
(``faults.POINTS``) is the single spelling authority; this checker
cross-references it against (a) every literal point passed to
``maybe_fail`` / ``should_drop`` / ``_inject`` in the codebase and (b)
the ``point:action`` specs appearing in tests — an injection point no
test ever fires is a degraded-mode path with no drill.
"""

from __future__ import annotations

import ast
import os
import re

from .base import Finding, RepoChecker, SourceFile

FAULT_CALLS = frozenset({"maybe_fail", "should_drop", "_inject",
                         "link_cut", "link_delay"})


def _declared_points(files: list[SourceFile]
                     ) -> tuple[dict[str, tuple[str, int]], str | None]:
    """POINTS registry entries -> (path, line); also the faults.py path."""
    declared: dict[str, tuple[str, int]] = {}
    faults_path: str | None = None
    for f in files:
        if not f.path.endswith("faults.py"):
            continue
        faults_path = f.path
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == "POINTS"
                       for t in node.targets):
                continue
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    declared[c.value] = (f.path, c.lineno)
    return declared, faults_path


def _used_points(files: list[SourceFile]) -> dict[str, list[tuple[str, int]]]:
    used: dict[str, list[tuple[str, int]]] = {}
    for f in files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name not in FAULT_CALLS:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                used.setdefault(arg.value, []).append((f.path, node.lineno))
    return used


def _test_specs(repo_root: str) -> str:
    chunks: list[str] = []
    tests = os.path.join(repo_root, "tests")
    if os.path.isdir(tests):
        for name in sorted(os.listdir(tests)):
            if name.endswith(".py"):
                try:
                    with open(os.path.join(tests, name),
                              encoding="utf-8") as fh:
                        chunks.append(fh.read())
                except OSError:
                    continue
    return "\n".join(chunks)


class FaultPointChecker(RepoChecker):
    name = "fault-point-registry"

    def check_repo(self, files: list[SourceFile],
                   repo_root: str) -> list[Finding]:
        findings: list[Finding] = []
        declared, faults_path = _declared_points(files)
        used = _used_points(files)
        if faults_path is None:
            return findings  # fixture runs without a faults module
        if not declared:
            findings.append(Finding(
                self.name, faults_path, 1,
                "faults.py declares no POINTS registry — every injection "
                "point must be declared in faults.POINTS"))
            return findings

        for point, sites in sorted(used.items()):
            if point not in declared:
                path, line = sites[0]
                findings.append(Finding(
                    self.name, path, line,
                    f"fault point {point!r} is used here but not declared "
                    f"in faults.POINTS (typo, or add it to the registry)"))

        test_text = _test_specs(repo_root)
        for point, (path, line) in sorted(declared.items()):
            if point not in used:
                findings.append(Finding(
                    self.name, path, line,
                    f"fault point {point!r} is declared but no code site "
                    f"injects it — dead registry entry"))
                continue
            if not re.search(
                    re.escape(point)
                    + r":(error|raise|drop|latency|poison_row)",
                    test_text):
                findings.append(Finding(
                    self.name, path, line,
                    f"fault point {point!r} is never exercised by any "
                    f"test (no '{point}:<action>' spec under tests/) — "
                    f"a degraded-mode path with no drill"))
        return findings
