"""ReplicationApplier: the follower side of WAL shipping.

Runs on a replica or standby server's event loop, keeps one chunked
feed connection to the primary (``GET /replication/wal``), and applies
every shipped record into the local store at the primary's exact RVs —
watch events fan out locally (replica informers stay live), the record
lands in the local WAL (replica durability), and ``repl_applied_rv`` /
``repl_lag_records`` make the follower's honesty observable.

Standby promotion rides the PR 2 circuit machinery: when the feed dies,
the applier probes the primary's ``/healthz`` through a
:class:`~kcp_tpu.utils.circuit.CircuitBreaker`; once the breaker is OPEN
and stays open past the hysteresis window, the standby promotes — bumps
the replication epoch (persisted with the WAL), opens the store for
writes, and fences the old primary (best-effort POST
``/replication/fence`` retried in the background) so a zombie coming
back cannot commit.

``repl.apply`` (error = the apply loop drops the connection and
re-resumes from the applied RV) and ``repl.promote`` (error = the
promotion attempt aborts and retries after the next probe cycle) are
KCP_FAULTS injection points.
"""

from __future__ import annotations

import asyncio
import http.client
import json
import logging
import time
from urllib.parse import urlsplit

from .. import obs
from ..faults import link_fault, maybe_fail
from ..server.rest import RestWatch, _status_error
from ..utils import errors
from ..utils.circuit import CircuitBreaker
from ..utils.trace import REGISTRY

log = logging.getLogger(__name__)


class _FeedStream(RestWatch):
    """The replication feed as parsed ndjson messages (reuses the
    RestWatch chunked-transfer reassembly; the Event wrapping of the
    watch wire format does not apply here)."""

    def _handle_line(self, msg: dict) -> None:
        self._events.put_nowait(msg)

    async def next(self) -> dict | None:
        self._ensure_started()
        if self._closed and self._events.empty():
            return None
        item = await self._events.get()
        if item is None:
            self._events.put_nowait(None)
            return None
        return item

    def drain_msgs(self) -> list[dict]:
        out: list[dict] = []
        while not self._events.empty():
            item = self._events.get_nowait()
            if item is None:
                self._events.put_nowait(None)
                break
            out.append(item)
        return out


class ReplicationApplier:
    """Follow a primary's WAL feed into a local LogicalStore."""

    def __init__(self, store, primary_url: str, role: str = "replica",
                 token: str = "", ca_data=None, ca_file: str | None = None,
                 hysteresis_s: float = 3.0, probe_interval_s: float = 0.3,
                 on_promote=None):
        if role not in ("replica", "standby"):
            raise ValueError(f"unknown replication role {role!r}")
        self.store = store
        # ``primary_url`` may be a comma-separated CANDIDATE list
        # (url1,url2 — the KCP_PRIMARY form): the first entry is the
        # configured primary; a replica whose primary stays dead or
        # fenced past the hysteresis window probes the candidates in
        # order and re-homes onto whichever one answers as the live
        # primary (the promoted standby after a failover)
        self.candidates = [u.strip().rstrip("/")
                           for u in primary_url.split(",") if u.strip()]
        if not self.candidates:
            raise ValueError("replication applier needs a primary URL")
        self.role = role
        self.token = token
        self._ca_data = ca_data
        self._ca_file = ca_file
        self.hysteresis_s = hysteresis_s
        self.probe_interval_s = probe_interval_s
        self.on_promote = on_promote
        self.promoted = False
        self.connected = False
        self.last_seen_rv = 0  # primary's rv from the stream header/records
        self._sub_id: int | None = None
        self._stream_epoch = 0
        self._task: asyncio.Task | None = None
        self._fence_task: asyncio.Task | None = None
        self._stopped = False
        # RV-barrier waiters (KEP-2340 consistent reads): rv -> shared
        # future resolved when applied_rv reaches it. Same coalescing
        # discipline as the hub's semi-sync waiters.
        self._barrier_futs: dict[int, asyncio.Future] = {}
        # recent apply throughput (records/s, EWMA over feed batches) —
        # the denominator of the lag-shed Retry-After hint
        self._apply_rate = 0.0
        self._rate_t0 = 0.0
        self._set_primary(self.candidates[0])
        self._rehomes = REGISTRY.counter(
            "repl_rehome_total",
            "times a follower re-resolved its feed onto another primary "
            "candidate (the promoted standby after a failover)")
        self._applied_gauge = REGISTRY.gauge(
            "repl_applied_rv",
            "highest primary RV this follower has applied")
        self._lag_gauge = REGISTRY.gauge(
            "repl_lag_records",
            "records between the primary's last seen RV and this "
            "follower's applied RV")
        self._applied_total = REGISTRY.counter(
            "repl_apply_records_total",
            "WAL records applied from the replication feed")
        self._frontier_gauge = REGISTRY.gauge(
            "repl_frontier_rv",
            "primary's commit RV as last seen by this follower (stream "
            "header, records, or PROGRESS heartbeats)")

    def _set_primary(self, url: str) -> None:
        """Point the feed/probe/ack/fence plumbing at ``url`` (the
        initial primary, or a re-homed candidate) with a fresh breaker —
        the new primary must not inherit the dead one's open circuit."""
        self.primary_url = url
        parts = urlsplit(url)
        self._host = parts.hostname or "127.0.0.1"
        self._tls = parts.scheme == "https"
        self._port = parts.port or (443 if self._tls else 80)
        self._ssl = None
        if self._tls:
            from ..server.certs import client_context

            self._ssl = client_context(self._ca_data, self._ca_file)
        # the primary-death detector: transport probes through a breaker,
        # exactly like any other dead-peer detection in this codebase
        self.breaker = CircuitBreaker(
            f"repl_primary_{self._host}_{self._port}", failure_threshold=3,
            reset_timeout=self.probe_interval_s)

    # ------------------------------------------------------------ public

    @property
    def applied_rv(self) -> int:
        return self.store.resource_version

    @property
    def lag_records(self) -> int:
        return max(0, self.last_seen_rv - self.store.resource_version)

    @property
    def frontier_rv(self) -> int:
        """The primary's commit frontier as last observed (header, WAL
        records, or PROGRESS heartbeats on an idle feed)."""
        return max(self.last_seen_rv, self.store.resource_version)

    @property
    def apply_rate(self) -> float:
        """Recent apply throughput in records/s (0.0 until measured)."""
        return self._apply_rate

    async def wait_applied(self, rv: int, timeout_s: float) -> bool:
        """RV-barrier for consistent reads: park until this follower's
        applied RV reaches ``rv`` or ``timeout_s`` expires. Waiters at
        the same RV share one future (the hub semi-sync discipline).
        True when the barrier is satisfied; False on timeout — the
        caller answers the typed 504 and the read falls back to the
        primary.

        Fast-fail: when ``rv`` is above the frontier AND the feed is
        down, no in-flight record can ever satisfy the barrier — the
        progress-notify frontier is exactly the proof that this
        follower has never even seen the RV. Parking the full window
        would only slow the caller's fallback (a dead primary mid
        failover would turn every pinned read into a full timeout)."""
        if self.store.resource_version >= rv or self.promoted:
            return True
        if rv > self.frontier_rv and not self.connected:
            return False
        # reachability: the EWMA apply rate bounds how far this
        # follower can catch up inside the window — a barrier that is
        # provably out of reach (2x slack for bursty batches) answers
        # immediately too. A wrong fast-fail only costs one primary
        # read; a doomed park costs the caller the whole window on
        # every read while the follower is drowning
        rate = self._apply_rate
        if rate > 0.0 and (rv - self.store.resource_version) \
                > rate * timeout_s * 2.0:
            return False
        fut = self._barrier_futs.get(rv)
        if fut is None or fut.done():
            fut = asyncio.get_running_loop().create_future()
            self._barrier_futs[rv] = fut
        try:
            # shield: the shared future must survive one reader's timeout
            await asyncio.wait_for(asyncio.shield(fut), timeout=timeout_s)
            # releases fire on apply, promote, AND stop: re-check rather
            # than trusting the future (a stop-path release must not
            # pretend the barrier was reached)
            return self.store.resource_version >= rv or self.promoted
        except asyncio.TimeoutError:
            return self.store.resource_version >= rv

    def _release_barriers(self) -> None:
        if not self._barrier_futs:
            return
        applied = self.store.resource_version
        for rv in [r for r, f in self._barrier_futs.items()
                   if r <= applied or f.done()]:
            fut = self._barrier_futs.pop(rv)
            if not fut.done():
                fut.set_result(True)

    def _release_all_barriers(self) -> None:
        """Promotion/shutdown: nothing will ever apply again on this
        path — release every parked reader (they re-check applied_rv)."""
        for fut in self._barrier_futs.values():
            if not fut.done():
                fut.set_result(True)
        self._barrier_futs.clear()

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        self._stopped = True
        self._release_all_barriers()
        for t in (self._task, self._fence_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        self._task = self._fence_task = None

    # -------------------------------------------------------------- loop

    async def _run(self) -> None:
        down_since: float | None = None
        loop = asyncio.get_running_loop()
        while not self._stopped and not self.promoted:
            try:
                streamed = await self._follow_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # injected apply faults, garbled feed
                log.warning("replication feed error: %s", e)
                streamed = False
            if self._stopped or self.promoted:
                return
            if streamed:
                down_since = None  # we WERE connected; restart the clock
            info = await loop.run_in_executor(None, self._probe_blocking,
                                              None)
            # a reachable primary is healthy for a standby (promotion is
            # about primary DEATH); a replica additionally treats a
            # FENCED primary as gone — its feed can never commit again,
            # so the re-home clock runs even though the process answers
            healthy = info is not None
            if healthy and self.role == "replica" and info.get("fenced"):
                healthy = False
            if healthy:
                self.breaker.record_success()
                down_since = None
            else:
                self.breaker.record_failure()
                if down_since is None:
                    down_since = loop.time()
                from ..utils.circuit import OPEN

                if (self.breaker.state == OPEN
                        and loop.time() - down_since >= self.hysteresis_s):
                    if self.role == "standby":
                        try:
                            await self._promote()
                            return
                        except asyncio.CancelledError:
                            raise
                        except Exception as e:
                            # injected repl.promote fault (or a transient
                            # persistence failure): retry next cycle — the
                            # hysteresis clock keeps running
                            log.warning("promotion attempt aborted: %s", e)
                    elif len(self.candidates) > 1:
                        # replica re-homing: the configured primary is
                        # dead or fenced past hysteresis — probe the
                        # candidate list for the promoted primary and
                        # follow the live epoch
                        if await loop.run_in_executor(
                                None, self._rehome_blocking):
                            down_since = None
            await asyncio.sleep(self.probe_interval_s)

    def _probe_blocking(self, url: str | None = None) -> dict | None:
        """One short-timeout ``/replication/status`` probe (executor
        thread) — the liveness AND role/epoch/fence oracle; None when
        unreachable. ``url`` overrides the current primary (candidate
        probes during re-homing)."""
        if url is None:
            host, port = self._host, self._port
            tls, ssl_ctx = self._tls, self._ssl
        else:
            parts = urlsplit(url)
            host = parts.hostname or "127.0.0.1"
            tls = parts.scheme == "https"
            port = parts.port or (443 if tls else 80)
            ssl_ctx = None
            if tls:
                from ..server.certs import client_context

                ssl_ctx = client_context(self._ca_data, self._ca_file)
        conn = None
        try:
            # a peer-scoped link.partition makes the probe target
            # unreachable from THIS follower (ConnectionError -> None),
            # which is what drives the breaker open and the promotion
            d = link_fault(self.role, f"{host}:{port}")
            if d:
                time.sleep(d)
            if tls:
                conn = http.client.HTTPSConnection(
                    host, port, timeout=1.0, context=ssl_ctx)
            else:
                conn = http.client.HTTPConnection(host, port, timeout=1.0)
            conn.request("GET", "/replication/status")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return None
            out = json.loads(body)
            return out if isinstance(out, dict) else None
        except (ConnectionError, OSError, http.client.HTTPException,
                ValueError):
            return None
        finally:
            if conn is not None:
                conn.close()

    def _rehome_blocking(self) -> bool:
        """Probe the candidate list in order and adopt the first live,
        unfenced PRIMARY at our epoch or newer (the promoted standby
        after a failover; an older epoch is a zombie). Runs on the
        executor thread the probe loop already awaits, so the feed task
        never observes a half-switched primary. True when re-pointed."""
        for url in self.candidates:
            if url == self.primary_url:
                continue
            info = self._probe_blocking(url)
            if info is None or info.get("fenced"):
                continue
            if info.get("role") != "primary":
                continue  # an unpromoted standby cannot feed us writes yet
            if int(info.get("epoch", 0) or 0) < self.store.epoch:
                continue  # a fenced-epoch zombie answering before its fence
            log.warning("re-homing replication feed: %s -> %s (epoch %s)",
                        self.primary_url, url, info.get("epoch"))
            self._set_primary(url)
            self._rehomes.inc()
            return True
        return False

    async def _follow_once(self) -> bool:
        """One feed connection: catch up, then apply live records until
        the stream dies. Returns True if the stream delivered a valid
        header (i.e. the primary was alive at some point)."""
        query = (f"sinceRV={self.store.resource_version}"
                 f"&epoch={self.store.epoch}&role={self.role}")
        ws = _FeedStream(self._host, self._port,
                         f"/replication/wal?{query}", "",
                         token=self.token, ssl_context=self._ssl)
        got_header = False
        in_snapshot = False

        async def _link_sentinel() -> None:
            # WAN realism: a peer-scoped partition must sever an
            # ESTABLISHED feed, not just refuse new connects — an idle
            # stream would otherwise keep a partitioned standby happy
            # forever and promotion would never fire. Poll the link
            # fault point and kill the stream the moment the path to
            # the primary is cut (real TCP would time out the same way).
            while True:
                await asyncio.sleep(self.probe_interval_s)
                try:
                    link_fault(self.role, f"{self._host}:{self._port}")
                except ConnectionError:
                    ws.close()
                    return

        sentinel = asyncio.ensure_future(_link_sentinel())
        try:
            while True:
                msg = await ws.next()
                if msg is None:
                    self.connected = False
                    return got_header
                batch = [msg, *ws.drain_msgs()]
                delay = maybe_fail("repl.apply")
                if delay:
                    await asyncio.sleep(delay)
                applied = 0
                for m in batch:
                    mtype = m.get("type")
                    if mtype == "HEADER":
                        got_header = True
                        self.connected = True
                        self._sub_id = m.get("sub")
                        self._stream_epoch = int(m.get("epoch", 0))
                        if self._stream_epoch < self.store.epoch:
                            # a zombie primary from a fenced epoch: its
                            # feed must not rewind us (the hub normally
                            # self-fences first, but never trust a wire)
                            REGISTRY.counter(
                                "repl_fenced_writes_total").inc()
                            raise errors.GoneError(
                                f"feed epoch {self._stream_epoch} < local "
                                f"epoch {self.store.epoch}; refusing")
                        if self._stream_epoch > self.store.epoch:
                            self.store.set_epoch(self._stream_epoch)
                        self.last_seen_rv = max(self.last_seen_rv,
                                                int(m.get("rv", 0)))
                        if m.get("snapshot"):
                            in_snapshot = True
                            self.store.reset_for_resync()
                    elif mtype == "SNAP":
                        self.store.load_snapshot_object(m["key"], m["obj"])
                    elif mtype == "BARRIER":
                        in_snapshot = False
                        self.store.finish_resync(int(m["rv"]))
                        applied += 1
                    elif mtype == "PROGRESS":
                        # bodyless frontier heartbeat: the primary is
                        # quiet but alive — advance the frontier so
                        # repl_lag stays honest between records and
                        # RV-barrier reads can resolve "consistent"
                        self.last_seen_rv = max(self.last_seen_rv,
                                                int(m.get("rv", 0)))
                    elif mtype == "ERROR":
                        obj = m.get("object") or {}
                        raise _status_error(obj.get("code", 410),
                                            obj.get("reason", ""),
                                            obj.get("message", ""))
                    else:  # a WAL record
                        rv = int(m.get("rv", 0))
                        self.last_seen_rv = max(self.last_seen_rv, rv)
                        tctx = obs.ctx_from_wal(m.get("tc"))
                        t0 = time.time() if tctx is not None else 0.0
                        if self.store.apply_replicated(
                                m, epoch=self._stream_epoch):
                            applied += 1
                        if tctx is not None:
                            # the primary's sampled write rides the
                            # record: this follower's apply lands in ITS
                            # buffer under the same trace id, assembled
                            # by the router's /debug/trace scatter
                            obs.record_span(
                                "repl.apply", obs.TRACER.child(tctx),
                                tctx.span_id, t0, time.time() - t0,
                                {"rv": str(rv), "role": self.role})
                if applied:
                    self._applied_total.inc(applied)
                    now = time.monotonic()
                    if self._rate_t0:
                        dt = max(1e-6, now - self._rate_t0)
                        inst = applied / dt
                        self._apply_rate = (
                            inst if self._apply_rate == 0.0
                            else 0.7 * self._apply_rate + 0.3 * inst)
                    self._rate_t0 = now
                self._applied_gauge.set(self.store.resource_version)
                self._lag_gauge.set(self.lag_records)
                self._frontier_gauge.set(self.frontier_rv)
                self._release_barriers()
                if applied and not in_snapshot and self.role == "standby" \
                        and self._sub_id is not None:
                    await self._send_ack()
        finally:
            sentinel.cancel()
            ws.close()
            self.connected = False

    async def _send_ack(self) -> None:
        """Report the applied RV to the primary (semi-sync commits)."""
        sid, rv = self._sub_id, self.store.resource_version
        await asyncio.get_running_loop().run_in_executor(
            None, self._ack_blocking, sid, rv)

    def _ack_blocking(self, sid: int, rv: int) -> None:
        conn = None
        try:
            d = link_fault(self.role, f"{self._host}:{self._port}")
            if d:
                time.sleep(d)
            if self._tls:
                conn = http.client.HTTPSConnection(
                    self._host, self._port, timeout=5.0, context=self._ssl)
            else:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=5.0)
            body = json.dumps({"sub": sid, "rv": rv}).encode()
            headers = {"Content-Type": "application/json"}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            conn.request("POST", "/replication/ack", body=body,
                         headers=headers)
            conn.getresponse().read()
        except (ConnectionError, OSError, http.client.HTTPException):
            pass  # best-effort: a lost ack only delays the sync floor
        finally:
            if conn is not None:
                conn.close()

    # --------------------------------------------------------- promotion

    async def _promote(self) -> None:
        """Fence the old epoch, open for writes, become the primary."""
        delay = maybe_fail("repl.promote")
        if delay:
            await asyncio.sleep(delay)
        new_epoch = self.store.epoch + 1
        self.store.set_epoch(new_epoch)  # durable BEFORE serving writes
        self.store.read_only = None
        self.store.fenced = False
        self.store.reject_future_rv = False
        self.promoted = True
        self._release_all_barriers()
        REGISTRY.counter(
            "repl_promotions_total",
            "standby promotions to primary").inc()
        log.warning("standby PROMOTED to primary at epoch %d (rv %d); "
                    "fencing %s", new_epoch, self.store.resource_version,
                    self.primary_url)
        if self.on_promote is not None:
            self.on_promote()
        self._fence_task = asyncio.ensure_future(
            self._fence_old_primary(new_epoch))

    async def _fence_old_primary(self, epoch: int) -> None:
        """Best-effort fence of the superseded primary, retried with
        backoff: if the old process ever comes back as a zombie, its
        store goes read-only before a client can land a write on it."""
        backoff = 0.5
        while not self._stopped:
            ok = await asyncio.get_running_loop().run_in_executor(
                None, self._fence_blocking, epoch)
            if ok:
                log.info("old primary %s fenced at epoch %d",
                         self.primary_url, epoch)
                return
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)

    def _fence_blocking(self, epoch: int) -> bool:
        conn = None
        try:
            # the partition-during-promotion drill's key property: while
            # the old primary is unreachable the fence retries fail here,
            # and the fence must still land once the link heals
            d = link_fault(self.role, f"{self._host}:{self._port}")
            if d:
                time.sleep(d)
            if self._tls:
                conn = http.client.HTTPSConnection(
                    self._host, self._port, timeout=2.0, context=self._ssl)
            else:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=2.0)
            body = json.dumps({"epoch": epoch}).encode()
            headers = {"Content-Type": "application/json"}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            conn.request("POST", "/replication/fence", body=body,
                         headers=headers)
            resp = conn.getresponse()
            resp.read()
            return 200 <= resp.status < 300
        except (ConnectionError, OSError, http.client.HTTPException):
            return False
        finally:
            if conn is not None:
                conn.close()
