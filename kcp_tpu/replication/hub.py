"""ReplicationHub: the primary side of WAL shipping.

Attached to a :class:`~kcp_tpu.store.store.LogicalStore` via its
replication hook, the hub sees every committed WAL record (both
durability backends journal the same record dicts; in-memory stores
still emit them) and

- retains a bounded window of encoded record lines keyed by RV, so a
  reconnecting follower resumes from its applied RV with a cheap tail
  replay (the watch-cache discipline applied to the log itself);
- fans live records out to subscriber queues that the HTTP feed
  (``GET /replication/wal``) drains into chunked ndjson streams — one
  ``json.dumps`` per record regardless of follower count;
- falls back to a full snapshot stream (materialized synchronously on
  the serving loop, so it is a consistent cut) when a follower's RV
  predates the retained window;
- tracks standby acks for semi-synchronous commits: the REST write path
  can wait until every attached standby has applied a write's RV before
  acknowledging it, which is what makes "zero acknowledged-write loss"
  a property rather than a race;
- enforces epoch fencing at the feed boundary: a subscriber announcing
  a NEWER epoch proves this primary was superseded — the store fences
  itself (writes refuse 503) instead of diverging.

Wire format (ndjson lines over one chunked response):

    {"type":"HEADER","epoch":E,"rv":R,"sub":ID,"snapshot":bool}
    {"type":"SNAP","key":[...],"obj":{...}}          (snapshot mode)
    {"type":"BARRIER","rv":R}                        (snapshot end)
    {"op":"put"|"del"|"epoch", "key":[...], "rv":R, "obj":{...}}
    {"type":"ERROR","object":{Status}}               (terminal refusal)

``repl.ship`` is a KCP_FAULTS injection point on the feed path (error =
the stream dies and the follower reconnects; latency = ship lag).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from collections import deque

from ..faults import link_fault, maybe_fail
from ..utils.trace import REGISTRY

log = logging.getLogger(__name__)


def _progress_notify_s() -> float:
    """Progress-notify cadence (etcd WatchProgressRequest analog) in
    seconds; 0 disables heartbeats entirely."""
    try:
        ms = float(os.environ.get("KCP_PROGRESS_NOTIFY_MS", "500") or 0)
    except ValueError:
        ms = 500.0
    return max(0.0, ms / 1000.0)


class _Sub:
    """One attached follower: a live-record queue + its declared role."""

    def __init__(self, sid: int, role: str):
        self.sid = sid
        self.role = role
        self.q: asyncio.Queue[bytes] = asyncio.Queue()


class ReplicationHub:
    """Primary-side WAL shipper for one LogicalStore."""

    def __init__(self, store, window: int = 200_000,
                 sync_timeout_s: float = 5.0):
        self.store = store
        # (rv, encoded line) of recent committed records — the resume
        # window. Encoded once at commit; every subscriber splices the
        # same bytes (the encode-once discipline applied to the log).
        self._records: deque[tuple[int, bytes]] = deque(maxlen=window)
        self._subs: dict[int, _Sub] = {}
        self._next_sid = 1
        self._acked: dict[int, int] = {}  # standby sid -> applied rv
        self._waiters: list[tuple[int, asyncio.Future]] = []
        # semi-sync waiters coalesce per RV: every writer of one commit
        # window parks on the window's HIGH RV, so N writers share ONE
        # future and one standby ack releases them all (one RTT/window)
        self._wait_futs: dict[int, asyncio.Future] = {}
        self.sync_timeout_s = sync_timeout_s
        self._shipped = REGISTRY.counter(
            "repl_ship_records_total",
            "WAL records shipped to replication subscribers")
        self._subs_gauge = REGISTRY.gauge(
            "repl_subscribers",
            "attached replication subscribers (replicas + standbys)")
        self._degraded = REGISTRY.counter(
            "repl_sync_degraded_total",
            "writes acknowledged without standby confirmation because "
            "the semi-sync wait timed out")
        self._ack_batched = REGISTRY.counter(
            "repl_ack_batched_total",
            "semi-sync waiters that parked on an already-waiting commit "
            "window RV — writes released by a shared standby ack instead "
            "of their own round trip")
        self._progress = REGISTRY.counter(
            "repl_progress_notify_total",
            "PROGRESS heartbeat frames shipped on idle replication feeds "
            "(no record body, just the primary's commit RV) so quiet "
            "followers know the frontier")
        store.set_repl_hook(self.commit, self.commit_batch)

    # ------------------------------------------------------------- commit

    def commit(self, rec: dict) -> None:
        """Store hook: one committed WAL record. Runs synchronously on
        the store's owning loop, so window append + fan-out are atomic
        with respect to feed registration."""
        rv = int(rec.get("rv", 0) or self.store.resource_version)
        line = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
        self._records.append((rv, line))
        if self._subs:
            for sub in self._subs.values():
                sub.q.put_nowait(line)
            self._shipped.inc(len(self._subs))

    def commit_batch(self, recs: list[dict]) -> None:
        """Store batch hook: one flushed commit window. The resume
        window keeps per-RV lines (reconnect tails bisect by RV), but
        live subscribers get the whole window as ONE queue push — the
        feed writes it as one chunk, the follower applies it as one
        batch and answers ONE ack at the window's high RV."""
        lines = []
        for rec in recs:
            rv = int(rec.get("rv", 0) or self.store.resource_version)
            line = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
            self._records.append((rv, line))
            lines.append(line)
        if self._subs and lines:
            blob = b"".join(lines)
            for sub in self._subs.values():
                sub.q.put_nowait(blob)
            self._shipped.inc(len(lines) * len(self._subs))

    # ------------------------------------------------------ subscriptions

    @property
    def has_sync_subscribers(self) -> bool:
        return any(s.role == "standby" for s in self._subs.values())

    def _register(self, role: str) -> _Sub:
        sub = _Sub(self._next_sid, role)
        self._next_sid += 1
        self._subs[sub.sid] = sub
        self._subs_gauge.set(len(self._subs))
        return sub

    def _unregister(self, sub: _Sub) -> None:
        self._subs.pop(sub.sid, None)
        self._acked.pop(sub.sid, None)
        self._subs_gauge.set(len(self._subs))
        self._check_waiters()

    # -------------------------------------------------------- semi-sync

    def ack(self, sid: int, rv: int) -> None:
        """A standby reports its applied RV (POST /replication/ack)."""
        sub = self._subs.get(sid)
        if sub is None or sub.role != "standby":
            return
        self._acked[sid] = max(self._acked.get(sid, 0), int(rv))
        self._check_waiters()

    def _sync_floor(self) -> int | None:
        """min applied RV over attached standbys; None when there are
        none (async mode — nothing to wait for)."""
        sids = [s.sid for s in self._subs.values() if s.role == "standby"]
        if not sids:
            return None
        return min(self._acked.get(sid, 0) for sid in sids)

    def _check_waiters(self) -> None:
        floor = self._sync_floor()
        still: list[tuple[int, asyncio.Future]] = []
        for rv, fut in self._waiters:
            if fut.done():
                self._wait_futs.pop(rv, None)
                continue
            if floor is None or floor >= rv:
                fut.set_result(True)
                self._wait_futs.pop(rv, None)
            else:
                still.append((rv, fut))
        self._waiters = still

    async def wait_committed(self, rv: int) -> bool:
        """Semi-sync commit: wait until every attached standby has
        applied ``rv``. Returns immediately when no standby is attached
        (async replication — the WAL is the durability story). Waiters
        at the same RV share one future (a commit window's writers all
        park at the window's high RV — one standby ack releases the
        whole window, counted ``repl_ack_batched_total``). On timeout
        the write is acknowledged anyway, degraded and counted: a
        lagging standby must not take primary availability hostage."""
        floor = self._sync_floor()
        if floor is None or floor >= rv:
            return True
        fut = self._wait_futs.get(rv)
        if fut is None or fut.done():
            fut = asyncio.get_running_loop().create_future()
            self._wait_futs[rv] = fut
            self._waiters.append((rv, fut))
        else:
            self._ack_batched.inc()
        try:
            # shield: the shared future must survive one waiter's timeout
            await asyncio.wait_for(asyncio.shield(fut),
                                   timeout=self.sync_timeout_s)
            return True
        except asyncio.TimeoutError:
            self._degraded.inc()
            log.warning("semi-sync wait for rv %d timed out after %.1fs; "
                        "acknowledging degraded", rv, self.sync_timeout_s)
            return False

    # ------------------------------------------------------------- feed

    async def serve_feed(self, stream, since_rv: int, sub_epoch: int,
                         role: str, cluster: str | None = None) -> None:
        """Produce one follower's feed onto a StreamResponse: header,
        tail-or-snapshot catchup, then live records until the connection
        dies or a ``repl.ship`` fault kills it.

        ``cluster`` selects the migration transport: a snapshot of that
        one cluster's objects, BARRIER, done — no live phase. The caller
        (sharding/migrate.py) fences the cluster on this store FIRST, so
        the filtered snapshot IS the cluster's final state and the
        BARRIER rv bounds every RV it ever minted for it."""
        delay = maybe_fail("repl.ship")
        # WAN-link realism: feed-side delay/partition scoped per
        # subscriber role ("repl.feed" -> "replica"/"standby"/...) — a
        # ConnectionError here kills this one follower's stream exactly
        # like the wire dying, without touching co-subscribers
        delay += link_fault("repl.feed", role or "replica")
        if delay:
            await asyncio.sleep(delay)
        if sub_epoch > self.store.epoch:
            # the subscriber has seen a newer epoch than ours: a standby
            # promoted over this primary while we were partitioned. We
            # are the zombie — fence NOW, refuse the feed.
            self.store.fence(sub_epoch)
            await stream.send_json({"type": "ERROR", "object": {
                "kind": "Status", "apiVersion": "v1", "status": "Failure",
                "reason": "Expired", "code": 410,
                "message": f"superseded by epoch {sub_epoch}; "
                           f"this primary is fenced"}})
            return
        sub = self._register(role)
        try:
            # everything up to the first await is atomic on the loop:
            # records committed after registration land in sub.q, the
            # header/tail/snapshot cover everything at or before it
            rv_now = self.store.resource_version
            oldest = self._records[0][0] if self._records else None
            need_snapshot = cluster is not None or (since_rv < rv_now and (
                oldest is None or oldest > since_rv + 1))
            header = json.dumps({
                "type": "HEADER", "epoch": self.store.epoch, "rv": rv_now,
                "sub": sub.sid, "snapshot": need_snapshot,
            }).encode() + b"\n"
            # keys only, never an (key, obj) pair list: a whole-store
            # snapshot must not pin every object dict for the life of
            # the stream (under churn that doubles resident state; on
            # the migration transport the cluster is large by
            # definition). Objects are fetched per batch at send time —
            # migration clusters are fenced first so the bytes are the
            # final state; a standby's snapshot converges through the
            # live queue it registered for above (idempotent puts, and
            # a key deleted mid-stream is skipped here because its
            # DELETE record follows).
            if cluster is not None:
                snap_keys = [k for k in self.store._objects
                             if k[1] == cluster]
            elif need_snapshot:
                snap_keys = list(self.store._objects)
            else:
                snap_keys = []
                tail = [line for rv, line in self._records
                        if since_rv < rv <= rv_now]
            await stream.send_spans([header])
            if need_snapshot:
                objects = self.store._objects
                shipped = 0
                batch: list[bytes] = []
                for key in snap_keys:
                    obj = objects.get(key)
                    if obj is None:
                        continue
                    batch.append(json.dumps(
                        {"type": "SNAP", "key": list(key), "obj": obj},
                        separators=(",", ":")).encode() + b"\n")
                    shipped += 1
                    if len(batch) >= 256:
                        await stream.send_spans(batch)
                        batch = []
                batch.append(json.dumps(
                    {"type": "BARRIER", "rv": rv_now}).encode() + b"\n")
                await stream.send_spans(batch)
                self._shipped.inc(shipped)
                if cluster is not None:
                    # migration transport ends at the barrier: the
                    # cluster is fenced, nothing more can follow
                    return
            elif tail:
                # the catchup tail is encode-once bytes (each record was
                # serialized exactly once at commit): the raw-spans send
                # hands them to the transport with no whole-batch join
                await stream.send_spans(tail)
                self._shipped.inc(len(tail))
            notify_s = _progress_notify_s()
            while True:
                if notify_s:
                    try:
                        line = await asyncio.wait_for(sub.q.get(), notify_s)
                    except asyncio.TimeoutError:
                        # feed idle past the progress cadence: ship a
                        # bodyless frontier heartbeat so the follower can
                        # answer RV-barrier reads without a fresh record.
                        # NOT appended to _records — heartbeats must never
                        # occupy the RV-resume window.
                        hb = json.dumps(
                            {"type": "PROGRESS",
                             "epoch": self.store.epoch,
                             "rv": self.store.resource_version},
                            separators=(",", ":")).encode() + b"\n"
                        delay = maybe_fail("repl.ship")
                        delay += link_fault("repl.feed", role or "replica")
                        if delay:
                            await asyncio.sleep(delay)
                        await stream.send_spans([hb])
                        self._progress.inc()
                        continue
                else:
                    line = await sub.q.get()
                batch = [line]
                while not sub.q.empty():
                    batch.append(sub.q.get_nowait())
                # graceful drain: the b"" sentinel (hub.drain) arrives
                # AFTER every shipped record in FIFO order — flush what
                # precedes it, answer a terminal Status, and end the feed
                # so the follower reconnects against whoever serves next
                draining = b"" in batch
                if draining:
                    batch = [ln for ln in batch if ln]
                delay = maybe_fail("repl.ship")
                # per-batch WAN delay: a slow link to THIS follower lags
                # its applied RV without slowing the other subscribers
                delay += link_fault("repl.feed", role or "replica")
                if delay:
                    await asyncio.sleep(delay)
                if batch:
                    await stream.send_spans(batch)
                if draining:
                    await stream.send_json({"type": "ERROR", "object": {
                        "kind": "Status", "apiVersion": "v1",
                        "status": "Failure",
                        "reason": "ServiceUnavailable", "code": 503,
                        "message": "primary is draining; re-resolve and "
                                   "resume from your applied RV"}})
                    return
        finally:
            self._unregister(sub)

    def drain(self) -> None:
        """Graceful drain: every subscriber feed flushes its queued
        records and then terminates with an in-stream Status. Runs on
        the store's owning loop AFTER the last write committed, so the
        sentinel is ordered behind every shipped record."""
        for sub in list(self._subs.values()):
            sub.q.put_nowait(b"")
