"""HA replication: WAL shipping, RV-honest read replicas, promotion.

The log IS the replication transport: every committed store mutation is
already a WAL record (both the native binary engine and the JSON-lines
fallback journal the same record dicts), so the primary ships exactly
those records over the existing HTTP chunked-stream surface and a
follower replays them into a live :class:`~kcp_tpu.store.store.LogicalStore`
— watch events fan out on the follower, the encode-once byte caches
warm on the follower's own snapshots, and the follower's local WAL makes
it durable in its own right.

- :class:`~kcp_tpu.replication.hub.ReplicationHub` — primary side:
  record window + subscriber queues + semi-sync acks + fencing.
- :class:`~kcp_tpu.replication.applier.ReplicationApplier` — follower
  side: feed client, exact-RV apply, lag metrics, standby promotion.
"""

from .applier import ReplicationApplier
from .hub import ReplicationHub

__all__ = ["ReplicationApplier", "ReplicationHub"]
