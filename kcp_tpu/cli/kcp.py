"""``kcp start`` — run the control-plane server process.

The analog of the reference's cmd/kcp/kcp.go:15-63 (`kcp start` cobra
command): bring up storage + API server + in-process controllers and
serve until interrupted. Flags mirror pkg/server/config.go:45-112.

Usage:
    python -m kcp_tpu.cli.kcp start [--listen-port 6443] [--root-dir .kcp_tpu]
        [--in-memory] [--no-install-controllers] [--auto-publish-apis]
        [--resources-to-sync deployments.apps] [--syncer-mode push|pull|none]
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys

from ..server import Config, Server
from .help import fit_terminal, parser

DOC = """Start a kcp-tpu control plane: a minimal multi-tenant API server
serving many logical clusters from one store, with batched TPU-backed
reconcilers installed in-process.

kcp-tpu is a TPU-native re-design of the kcp prototype: per-tenant
reconcile loops run as vectorized JAX programs instead of one goroutine
per workspace."""


def build_parser():
    p = parser("kcp", DOC)
    sub = p.add_subparsers(dest="command", required=True)
    start = sub.add_parser("start", help="start the control plane",
                           description=fit_terminal(DOC))
    start.add_argument("--listen-host", default="127.0.0.1")
    start.add_argument("--listen-port", type=int, default=6443,
                       help="API port (reference default :6443)")
    start.add_argument("--root-dir", default=".kcp_tpu",
                       help="data directory (reference: .kcp/, server.go:80-94)")
    start.add_argument("--in-memory", action="store_true",
                       help="no WAL durability (testing)")
    start.add_argument("--no-install-controllers", action="store_true",
                       help="serve only; controllers run out-of-process "
                            "(reference: cmd/cluster-controller). This is "
                            "already the default when --store-server is "
                            "set (a frontend's controllers would block "
                            "the serving loop on remote-store calls)")
    start.add_argument("--force-install-controllers", action="store_true",
                       help="run in-process controllers even with "
                            "--store-server, accepting that a slow "
                            "storage backend can block the serving loop "
                            "and that no other process may run "
                            "controllers against the same backend")
    start.add_argument("--auto-publish-apis", action="store_true",
                       help="negotiated APIs publish without manual approval "
                            "(reference: --auto_publish_apis)")
    start.add_argument("--resources-to-sync", default="deployments.apps",
                       help="comma-separated resources synced to physical clusters")
    start.add_argument("--role",
                       choices=["shard", "router", "replica", "standby"],
                       default="shard",
                       help="shard: a normal control-plane server (the "
                            "default; shards of a fleet are just servers). "
                            "router: the sharded control plane's scatter-"
                            "gather frontend — no storage, no controllers; "
                            "single-cluster requests proxy to the owning "
                            "shard, wildcard list/watch merge across all "
                            "shards (kcp_tpu/sharding/). "
                            "replica: a read-only follower replaying the "
                            "--primary server's WAL feed, serving GET/LIST/"
                            "WATCH RV-honestly from its own store. "
                            "standby: a replica that promotes itself to "
                            "primary (fencing the old one) when the "
                            "primary stays dead past the hysteresis "
                            "window (kcp_tpu/replication/)")
    start.add_argument("--shards", default="",
                       help="router role: comma-separated [name=]url shard "
                            "list (env KCP_SHARDS is the fallback), e.g. "
                            "s0=http://h0:6443,s1=http://h1:6443; a shard "
                            "entry may append |-separated read replicas, "
                            "e.g. s0=http://h0:6443|http://h0r:6444")
    start.add_argument("--shard-name", default="",
                       help="shard role: this server's stable name in the "
                            "ring (env KCP_SHARD_NAME). With --ring-names "
                            "set, direct smart-client requests (the "
                            "X-Kcp-Ring-Epoch stamp) are verified against "
                            "HRW ownership — a stale-ring client gets a "
                            "typed 410 instead of the wrong shard's answer")
    start.add_argument("--ring-names", default="",
                       help="shard role: comma-separated names of every "
                            "shard in the ring (env KCP_RING_NAMES); names "
                            "alone determine HRW ownership, so no "
                            "addresses are needed to verify direct "
                            "requests")
    start.add_argument("--ring-epoch", type=int, default=0,
                       help="shard role: the ring epoch this shard was "
                            "(re)started under, stamped on ring-mismatch "
                            "410s (env KCP_RING_EPOCH, default 1)")
    start.add_argument("--primary", default="",
                       help="replica/standby roles: the primary server's "
                            "base URL (the /replication/wal feed source "
                            "and promotion health-probe target). A "
                            "replica accepts a comma-separated candidate "
                            "list (url1,url2): when its primary stays "
                            "dead or fenced past the hysteresis window "
                            "it probes the candidates in order and "
                            "re-homes onto the live promoted primary. "
                            "Env KCP_PRIMARY is the fallback")
    start.add_argument("--drain-timeout", type=float, default=None,
                       help="graceful-drain budget in seconds on SIGTERM "
                            "(env KCP_DRAIN_TIMEOUT_S, default 5.0): "
                            "stop accepting, finish in-flight requests, "
                            "send terminal Status to watchers, flush "
                            "replication subscribers, then exit; "
                            "whatever is still alive at the deadline is "
                            "cut off hard. SIGINT skips the drain")
    start.add_argument("--repl-hysteresis", type=float, default=None,
                       help="standby promotion hysteresis seconds (env "
                            "KCP_REPL_HYSTERESIS_S, default 3.0): how long "
                            "the primary's breaker must stay open before "
                            "the standby fences it and takes writes")
    start.add_argument("--repl-lag-max", type=int, default=None,
                       help="replica reads answer 503 past this many "
                            "records of replication lag (env "
                            "KCP_REPL_LAG_MAX; default 0 = serve any "
                            "staleness, RV-honestly)")
    start.add_argument("--store-server", default="",
                       help="serve against another kcp-tpu server's "
                            "storage (the --etcd-servers analog): this "
                            "process becomes a stateless frontend; run "
                            "controllers on exactly one process")
    start.add_argument("--store-token", default="",
                       help="bearer token for an RBAC-enabled storage "
                            "backend")
    start.add_argument("--store-ca-file", default=None,
                       help="CA bundle for a TLS storage backend")
    start.add_argument("--syncer-image", default="",
                       help="image the pull-mode installer deploys into "
                            "physical clusters (default: the installer's "
                            "DEFAULT_SYNCER_IMAGE; see contrib/syncer-image)")
    start.add_argument("--syncer-mode", choices=["push", "pull", "none"],
                       default="push")
    start.add_argument("--poll-interval", type=float, default=60.0,
                       help="cluster health/API-import poll seconds "
                            "(reference: cluster.go:22, apiimporter.go:37)")
    start.add_argument("--authz", action="store_true",
                       help="enforce RBAC-lite (bearer tokens + per-tenant "
                            "ClusterRole/Binding evaluation); admin token is "
                            "minted into admin.kubeconfig")
    start.add_argument("--admin-token", default="",
                       help="fixed admin bearer token (minted when empty)")
    start.add_argument("--pallas", action="store_true",
                       help="serve the fused Pallas decide+match kernel "
                            "instead of the XLA lanes (single-device)")
    start.add_argument("--no-tls", action="store_true",
                       help="serve plaintext HTTP instead of the default "
                            "self-signed TLS endpoint")
    start.add_argument("--mesh", default="",
                       help="serving-mesh spec to shard the fused reconcile "
                            "core over jax devices: N (tenants), NxM "
                            "(tenants x slots), NxMxK (hosts x tenants x "
                            "slots), or 'auto' (live topology; hosts-major "
                            "on a multi-host pod), e.g. --mesh 4x2")
    start.add_argument("--distributed", action="store_true",
                       help="form the jax process group before serving "
                            "(multi-host pods; see --coordinator)")
    start.add_argument("--coordinator", default="",
                       help="jax.distributed coordinator address "
                            "(host:port); env JAX_COORDINATOR also works")
    start.add_argument("--num-processes", type=int, default=None)
    start.add_argument("--process-id", type=int, default=None)
    start.add_argument("-v", "--verbosity", type=int, default=0)

    snap = sub.add_parser(
        "snapshot",
        help="compact the WAL offline (etcdctl-snapshot analog)",
        description="Load the store from its WAL, write a snapshot and "
                    "truncate the log. Run only while the server is down.")
    snap.add_argument("--root-dir", default=".kcp_tpu")
    snap.add_argument("-v", "--verbosity", type=int, default=0)
    return p


def config_from_args(args) -> Config:
    return Config(
        root_dir=args.root_dir,
        listen_host=args.listen_host,
        listen_port=args.listen_port,
        durable=not args.in_memory,
        # tri-state: an explicit --no-install-controllers wins; a forced
        # install wins over the store-server default; otherwise None lets
        # the server resolve (False with --store-server, True embedded)
        install_controllers=(
            False if args.no_install_controllers
            else True if args.force_install_controllers
            else None),
        force_remote_controllers=args.force_install_controllers,
        auto_publish_apis=args.auto_publish_apis,
        resources_to_sync=[r for r in args.resources_to_sync.split(",") if r],
        syncer_mode=args.syncer_mode,
        syncer_image=args.syncer_image,
        store_server=args.store_server,
        store_token=args.store_token,
        store_ca_file=args.store_ca_file,
        role=args.role,
        shards=args.shards,
        shard_name=args.shard_name,
        ring_names=args.ring_names,
        ring_epoch=args.ring_epoch,
        primary=args.primary,
        repl_hysteresis_s=args.repl_hysteresis,
        repl_lag_max=args.repl_lag_max,
        drain_timeout_s=args.drain_timeout,
        poll_interval=args.poll_interval,
        import_poll_interval=args.poll_interval,
        authz=args.authz,
        admin_token=args.admin_token,
        tls=not args.no_tls,
        pallas=args.pallas,
        mesh=args.mesh,
    )


async def serve(config: Config) -> None:
    server = Server(config)

    async def announce(s: Server) -> None:
        # parseable by wrapping scripts (the reference prints the admin
        # kubeconfig path at startup for the same purpose)
        print(f"kcp-tpu serving at {s.address}", flush=True)

    server.add_post_start_hook(announce)
    loop = asyncio.get_event_loop()

    draining = False

    def _graceful() -> None:
        # SIGTERM: drain first (stop accepting, finish in-flight, send
        # terminal Status to watchers, flush replication), THEN stop. A
        # second SIGTERM — or a drain abort — falls through to the
        # immediate stop.
        nonlocal draining
        if draining:
            server.stop()
            return
        draining = True

        async def _drain_then_stop() -> None:
            try:
                await server.drain()
            finally:
                server.stop()

        asyncio.ensure_future(_drain_then_stop())

    try:
        loop.add_signal_handler(signal.SIGINT, server.stop)
        loop.add_signal_handler(signal.SIGTERM, _graceful)
    except NotImplementedError:  # non-unix
        pass
    await server.run()


def snapshot_cmd(args) -> int:
    """Offline WAL compaction: replay, snapshot, truncate, report."""
    import os

    from ..store import LogicalStore

    wal = os.path.join(args.root_dir, "store.wal")
    if not os.path.exists(wal) and not os.path.exists(wal + ".snap"):
        print(f"no WAL at {wal}", file=sys.stderr)
        return 1
    store = LogicalStore(wal_path=wal)
    objects, rv = len(store), store.resource_version
    store.snapshot()
    store.close()
    print(f"compacted {wal}: {objects} objects at rv {rv}")
    return 0


def main(argv: list[str] | None = None) -> int:
    from . import apply_platform_env

    apply_platform_env()
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity > 0 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    if args.command == "snapshot":
        return snapshot_cmd(args)
    if getattr(args, "distributed", False):
        from ..parallel.distributed import init_distributed

        init_distributed(coordinator=args.coordinator or None,
                         num_processes=args.num_processes,
                         process_id=args.process_id)
    asyncio.run(serve(config_from_args(args)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
