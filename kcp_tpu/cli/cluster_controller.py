"""Standalone cluster + apiresource controllers against a running kcp.

The analog of the reference's cmd/cluster-controller/main.go:27-87: for a
server started with --no-install-controllers, this process connects over
HTTP (the EnableMultiCluster wildcard client, main.go:41) and runs the
cluster, apiresource-negotiation, CRD-lifecycle, and deployment-splitter
controllers out-of-process.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys

from ..physical import PhysicalRegistry
from ..server.rest import MultiClusterRestClient, RestClient
from .help import parser

DOC = """Run the kcp-tpu control-plane controllers out-of-process against a
running kcp-tpu server. Registered Cluster resources get API importers and
syncers; imported schemas are negotiated into published APIs; root
Deployments are split across clusters."""


def build_parser():
    p = parser("cluster-controller", DOC)
    p.add_argument("--server", default="http://127.0.0.1:6443",
                   help="kcp-tpu API server URL")
    p.add_argument("--ca-file", default=None,
                   help="CA bundle for an https --server")
    p.add_argument("--resources-to-sync", default="deployments.apps")
    p.add_argument("--syncer-mode", choices=["push", "pull", "none"], default="push")
    p.add_argument("--syncer-image", default="",
                   help="image the pull-mode installer deploys (default: the "
                        "installer's DEFAULT_SYNCER_IMAGE; see "
                        "contrib/syncer-image)")
    p.add_argument("--auto-publish-apis", action="store_true")
    p.add_argument("--backend", choices=["tpu", "host"], default="tpu",
                   help="reconcile decision backend (batched device kernels "
                        "vs pure-host reference path)")
    p.add_argument("--poll-interval", type=float, default=60.0)
    p.add_argument("--num-threads", type=int, default=2,
                   help="workers per controller (reference: Start(2), "
                        "server.go:241,250)")
    return p


async def run(args) -> None:
    from ..reconcilers.apiresource import NegotiationController
    from ..reconcilers.cluster import ClusterController, SyncerMode
    from ..reconcilers.crdlifecycle import CRDLifecycleController
    from ..reconcilers.deployment import DeploymentSplitter

    client = MultiClusterRestClient(args.server, ca_file=args.ca_file)
    registry = PhysicalRegistry()
    # physical clusters reachable over HTTP resolve to REST clients
    registry.register_factory("http", lambda url: RestClient(url, cluster="default"))
    registry.register_factory("https", lambda url: RestClient(url, cluster="default"))

    mode = {"push": SyncerMode.PUSH, "pull": SyncerMode.PULL,
            "none": SyncerMode.NONE}[args.syncer_mode]
    controllers = [
        NegotiationController(client, auto_publish=args.auto_publish_apis,
                              backend=args.backend),
        CRDLifecycleController(client),
        ClusterController(
            client, registry,
            resources_to_sync=[r for r in args.resources_to_sync.split(",") if r],
            mode=mode, backend=args.backend,
            poll_interval=args.poll_interval,
            import_poll_interval=args.poll_interval,
            **({"syncer_image": args.syncer_image}
               if args.syncer_image else {})),
        DeploymentSplitter(client),
    ]
    for c in controllers:
        if isinstance(c, (NegotiationController, ClusterController)):
            await c.start(num_workers=args.num_threads)
        else:
            await c.start()

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    for c in reversed(controllers):
        await c.stop()


def main(argv: list[str] | None = None) -> int:
    from . import apply_platform_env

    apply_platform_env()
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
