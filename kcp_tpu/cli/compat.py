"""compat — check two CRD schemas for compatibility, optionally print LCD.

The analog of the reference's cmd/compat/main.go:19-76: load two CRD YAML
files, run the structural-schema compatibility check, and exit non-zero on
incompatibility; --lcd prints the lowest-common-denominator schema.
"""

from __future__ import annotations

import sys

import yaml

from ..schemacompat import ensure_structural_schema_compatibility
from .help import parser

DOC = """Compare the schemas of two CustomResourceDefinition YAML files.
Exits 0 when the new CRD is compatible with the existing one; prints the
incompatibilities and exits 1 otherwise. With --lcd, prints the lowest
common denominator schema (narrowing the existing schema where needed)."""


def _schema_of(crd: dict) -> dict:
    """First served version's openAPIV3Schema."""
    for v in crd.get("spec", {}).get("versions", []):
        if not v.get("served", True):
            continue
        schema = (v.get("schema") or {}).get("openAPIV3Schema")
        if schema:
            return schema
    return crd.get("spec", {}).get("validation", {}).get("openAPIV3Schema", {})


def build_parser():
    p = parser("compat", DOC)
    p.add_argument("existing", help="existing CRD YAML file")
    p.add_argument("new", help="new CRD YAML file")
    p.add_argument("--lcd", action="store_true",
                   help="narrow to and print the LCD schema "
                        "(reference: --lcd flag)")
    return p


def main(argv: list[str] | None = None) -> int:
    from . import apply_platform_env

    apply_platform_env()
    args = build_parser().parse_args(argv)
    with open(args.existing, encoding="utf-8") as f:
        existing = yaml.safe_load(f)
    with open(args.new, encoding="utf-8") as f:
        new = yaml.safe_load(f)
    lcd, errs = ensure_structural_schema_compatibility(
        _schema_of(existing), _schema_of(new), narrow_existing=args.lcd)
    if errs and not args.lcd:
        for e in errs:
            print(e, file=sys.stderr)
        return 1
    if args.lcd:
        yaml.safe_dump(lcd, sys.stdout, sort_keys=False)
    else:
        print("compatible")
    return 0


if __name__ == "__main__":
    sys.exit(main())
