"""kcp_tpu.cli — the CLI binaries (reference: cmd/).

Each module is runnable with ``python -m kcp_tpu.cli.<name>``:

- ``kcp``                  the control-plane server (cmd/kcp)
- ``cluster_controller``   standalone controllers (cmd/cluster-controller)
- ``syncer``               standalone spec/status syncer (cmd/syncer)
- ``deployment_splitter``  standalone splitter (cmd/deployment-splitter)
- ``crd_puller``           dump cluster APIs as CRD YAML (cmd/crd-puller)
- ``compat``               CRD schema compat / LCD check (cmd/compat)
"""
