"""kcp_tpu.cli — the CLI binaries (reference: cmd/).

Each module is runnable with ``python -m kcp_tpu.cli.<name>``:

- ``kcp``                  the control-plane server (cmd/kcp)
- ``cluster_controller``   standalone controllers (cmd/cluster-controller)
- ``syncer``               standalone spec/status syncer (cmd/syncer)
- ``deployment_splitter``  standalone splitter (cmd/deployment-splitter)
- ``crd_puller``           dump cluster APIs as CRD YAML (cmd/crd-puller)
- ``compat``               CRD schema compat / LCD check (cmd/compat)
"""

import os


def apply_platform_env() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` override from the shell.

    On images whose sitecustomize registers a TPU plugin before user
    code runs, the env var alone may not take for plain scripts; the
    config lever is the one that works. Called by each binary's main
    before any jax-using import so ``JAX_PLATFORMS=cpu python -m
    kcp_tpu.cli.kcp start`` deterministically stays off the device.
    """
    want = os.environ.get("JAX_PLATFORMS", "")
    if want and want != "axon":
        try:
            import jax

            jax.config.update("jax_platforms", want)
        except Exception:  # noqa: BLE001 — stay on the default platform
            pass
