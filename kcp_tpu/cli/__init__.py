"""kcp_tpu.cli — the CLI binaries (reference: cmd/).

Each module is runnable with ``python -m kcp_tpu.cli.<name>``:

- ``kcp``                  the control-plane server (cmd/kcp)
- ``cluster_controller``   standalone controllers (cmd/cluster-controller)
- ``syncer``               standalone spec/status syncer (cmd/syncer)
- ``deployment_splitter``  standalone splitter (cmd/deployment-splitter)
- ``crd_puller``           dump cluster APIs as CRD YAML (cmd/crd-puller)
- ``compat``               CRD schema compat / LCD check (cmd/compat)
"""

import os


def apply_platform_env() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` override from the shell.

    On images whose sitecustomize registers a TPU plugin before user
    code runs, the env var alone may not take for plain scripts; the
    config lever is the one that works. Called by each binary's main
    before any jax-using import so ``JAX_PLATFORMS=cpu python -m
    kcp_tpu.cli.kcp start`` deterministically stays off the device.
    """
    want = os.environ.get("JAX_PLATFORMS", "")
    if want and want != "axon":
        try:
            import jax

            jax.config.update("jax_platforms", want)
        except Exception:  # noqa: BLE001 — stay on the default platform
            pass
    enable_compilation_cache()


def enable_compilation_cache(default_path: str | None = None) -> None:
    """Persistent XLA compilation cache for every binary: a recompile of
    the fused step is a seconds-long serving stall (p99 poison), and the
    cache also turns restart warmup from ~30 s of compiles into reads.
    Opt out with KCP_NO_COMPILE_CACHE=1; relocate with KCP_COMPILE_CACHE.
    ``default_path`` overrides the built-in default (used by bench/tests
    to keep the cache repo-local); the env var wins over both.
    """
    if os.environ.get("KCP_NO_COMPILE_CACHE") == "1":
        return
    path = os.environ.get("KCP_COMPILE_CACHE") or default_path or os.path.join(
        os.path.expanduser("~"), ".cache", "kcp_tpu", "xla")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
