"""Terminal-width-aware help formatting.

The analog of the reference's pkg/cmd/help/doc.go (Doc/FitTerminal):
reflow long description text to the current terminal width so CLI help
stays readable in narrow terminals.
"""

from __future__ import annotations

import argparse
import shutil
import textwrap


def fit_terminal(text: str, width: int | None = None) -> str:
    """Reflow paragraphs to the terminal width (reference: FitTerminal)."""
    if width is None:
        width = min(shutil.get_terminal_size((80, 24)).columns, 100)
    out: list[str] = []
    for para in text.strip().split("\n\n"):
        # preserve indented/code blocks verbatim
        if para.startswith("  "):
            out.append(para)
        else:
            out.append(textwrap.fill(" ".join(para.split()), width))
    return "\n\n".join(out)


class DocFormatter(argparse.RawDescriptionHelpFormatter):
    """argparse formatter that width-fits the description."""


def parser(prog: str, doc: str) -> argparse.ArgumentParser:
    return argparse.ArgumentParser(
        prog=prog, description=fit_terminal(doc), formatter_class=DocFormatter)
