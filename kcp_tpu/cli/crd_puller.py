"""crd-puller — dump a cluster's API resources as CRD YAML files.

The analog of the reference's cmd/crd-puller/pull-crds.go:18-62: discover
the named resources on a cluster (existing CRDs or synthesized from
served types) and write one ``<plural>.<group>.yaml`` per resource.
"""

from __future__ import annotations

import logging
import sys

import yaml

from ..crdpuller import SchemaPuller
from ..server.rest import RestClient
from .help import parser

DOC = """Pull API resource schemas from a cluster and write them as
CustomResourceDefinition YAML files in the current directory."""


def build_parser():
    p = parser("crd-puller", DOC)
    p.add_argument("--server", required=True,
                   help="cluster URL (reference: -kubeconfig)")
    p.add_argument("--cluster", default="default")
    p.add_argument("--ca-file", default=None,
                   help="CA bundle for an https --server (e.g. the kcp "
                        "root dir's pki/ca.crt)")
    p.add_argument("--out-dir", default=".")
    p.add_argument("resources", nargs="+",
                   help="resources to pull, e.g. deployments.apps")
    return p


def main(argv: list[str] | None = None) -> int:
    from . import apply_platform_env

    apply_platform_env()
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    client = RestClient(args.server, cluster=args.cluster,
                        ca_file=args.ca_file)
    puller = SchemaPuller(client)
    pulled = puller.pull_crds(args.resources)
    rc = 0
    for res, crd in pulled.items():
        if crd is None:
            print(f"{res}: not served by {args.server}", file=sys.stderr)
            rc = 1
            continue
        path = f"{args.out_dir}/{crd['metadata']['name']}.yaml"
        with open(path, "w", encoding="utf-8") as f:
            yaml.safe_dump(crd, f, sort_keys=False)
        print(path)
    return rc


if __name__ == "__main__":
    sys.exit(main())
