"""Standalone syncer — spec↔status sync between two API servers.

The analog of the reference's cmd/syncer/main.go:24-73: connect upstream
(kcp, filtered to one logical cluster) and downstream (physical cluster),
then run the batched spec-downsync + status-upsync engine for the listed
resource types. In the reference this binary is what pull-mode deploys
into each physical cluster; the installed Deployment invokes the pod
form (``-from_kubeconfig /kcp/kubeconfig -cluster <name> <resources>``,
reference flags cmd/syncer/main.go:17-28), which this binary accepts
natively — the pull-mode emulator (kcp_tpu/physical/podrunner.py) parses
installed args through THIS parser so installer, binary, and emulator
share one argument surface.

Usage (direct):
    python -m kcp_tpu.cli.syncer --from-server http://kcp:6443 \
        --from-cluster tenant-a --to-server http://physical:8080 \
        --cluster us-east1 deployments.apps configmaps
Usage (pod form):
    python -m kcp_tpu.cli.syncer -from_kubeconfig /kcp/kubeconfig \
        --to-server http://physical:8080 -cluster us-east1 configmaps
"""

from __future__ import annotations

import asyncio
import json
import logging
import signal
import sys

from ..server.rest import RestClient
from .help import parser

DOC = """Sync specs down from a kcp-tpu logical cluster to a physical
cluster and statuses back up, for the listed resource types. Objects are
selected by the kcp.dev/cluster=<cluster> label; sync decisions are
computed by the batched TPU diff kernel."""


def build_parser(pod_form_only: bool = False):
    """The one syncer argument surface.

    ``pod_form_only`` relaxes the server flags for parsing an installed
    Deployment's args (the pod gets its downstream in-cluster, so the
    manifest carries no --to-server).
    """
    p = parser("syncer", DOC)
    p.add_argument("--from-server", default=None,
                   help="upstream kcp-tpu URL")
    p.add_argument("-from_kubeconfig", "--from-kubeconfig",
                   dest="from_kubeconfig", default=None,
                   help="path to an upstream kubeconfig (the pull-mode pod "
                        "mount; reference: -from_kubeconfig)")
    p.add_argument("--from-cluster", default="admin",
                   help="upstream logical cluster name")
    p.add_argument("--to-server", required=not pod_form_only, default=None,
                   help="downstream physical cluster URL (reference: "
                        "-to_kubeconfig / in-cluster config)")
    p.add_argument("--to-cluster", default="default",
                   help="downstream tenant (physical servers are usually "
                        "single-tenant: 'default')")
    p.add_argument("-cluster", "--cluster", dest="cluster", required=True,
                   help="sync target id — the kcp.dev/cluster label value "
                        "(reference: -cluster)")
    p.add_argument("--backend", choices=["tpu", "host"], default="tpu")
    p.add_argument("--from-ca-file", default=None,
                   help="CA bundle for an https --from-server (a kubeconfig's "
                        "certificate-authority-data is used automatically)")
    p.add_argument("--to-ca-file", default=None,
                   help="CA bundle for an https --to-server")
    p.add_argument("--mesh", default="",
                   help="serving-mesh spec (N, NxM or NxMxK) to shard the "
                        "fused core over jax devices")
    p.add_argument("resources", nargs="+",
                   help="resource types to sync, e.g. deployments.apps")
    return p


def kubeconfig_credentials(content: str) -> tuple[str, str, bytes | None]:
    """(server URL, bearer token, CA PEM or None) of the current context
    in a kubeconfig (the JSON shape render_kubeconfig writes; token empty
    when the server runs open, CA present when it serves TLS)."""
    import base64

    cfg = json.loads(content)
    current = cfg.get("current-context", "")
    ctx = next((c["context"] for c in cfg.get("contexts", [])
                if c.get("name") == current), None) or {}
    cluster_name = ctx.get("cluster") or current
    user_name = ctx.get("user", "")
    token = next((u.get("user", {}).get("token", "")
                  for u in cfg.get("users", []) if u.get("name") == user_name), "")
    for c in cfg.get("clusters", []):
        if c.get("name") == cluster_name:
            ca_b64 = c["cluster"].get("certificate-authority-data", "")
            ca = base64.b64decode(ca_b64) if ca_b64 else None
            return c["cluster"]["server"], token, ca
    raise ValueError(f"kubeconfig has no cluster {cluster_name!r}")


async def run(args) -> None:
    from ..syncer import start_syncer

    from_server, token, from_ca = args.from_server, "", None
    if from_server is None:
        if not args.from_kubeconfig:
            raise SystemExit("one of --from-server / -from_kubeconfig required")
        with open(args.from_kubeconfig, encoding="utf-8") as f:  # kcp-lint: disable=async-discipline -- one-shot CLI startup read; nothing is serving on this loop yet
            from_server, token, from_ca = kubeconfig_credentials(f.read())
    upstream = RestClient(from_server, cluster=args.from_cluster, token=token,
                          ca_data=from_ca,
                          ca_file=getattr(args, "from_ca_file", None))
    downstream = RestClient(args.to_server, cluster=args.to_cluster,
                            ca_file=getattr(args, "to_ca_file", None))
    mesh = None
    if getattr(args, "mesh", ""):
        from ..parallel.mesh import mesh_from_spec

        mesh = mesh_from_spec(args.mesh)
    syncer = await start_syncer(upstream, downstream, args.resources,
                                args.cluster, backend=args.backend, mesh=mesh)
    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    await syncer.stop()


def main(argv: list[str] | None = None) -> int:
    from . import apply_platform_env

    apply_platform_env()
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
