"""Standalone syncer — spec↔status sync between two API servers.

The analog of the reference's cmd/syncer/main.go:24-73: connect upstream
(kcp, filtered to one logical cluster) and downstream (physical cluster),
then run the batched spec-downsync + status-upsync engine for the listed
resource types. In the reference this binary is what pull-mode deploys
into each physical cluster.

Usage:
    python -m kcp_tpu.cli.syncer --from-server http://kcp:6443 \
        --from-cluster tenant-a --to-server http://physical:8080 \
        --cluster us-east1 deployments.apps configmaps
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys

from ..server.rest import RestClient
from .help import parser

DOC = """Sync specs down from a kcp-tpu logical cluster to a physical
cluster and statuses back up, for the listed resource types. Objects are
selected by the kcp.dev/cluster=<cluster> label; sync decisions are
computed by the batched TPU diff kernel."""


def build_parser():
    p = parser("syncer", DOC)
    p.add_argument("--from-server", required=True,
                   help="upstream kcp-tpu URL (reference: -from_kubeconfig)")
    p.add_argument("--from-cluster", default="admin",
                   help="upstream logical cluster name")
    p.add_argument("--to-server", required=True,
                   help="downstream physical cluster URL (reference: "
                        "-to_kubeconfig / in-cluster config)")
    p.add_argument("--to-cluster", default="default",
                   help="downstream tenant (physical servers are usually "
                        "single-tenant: 'default')")
    p.add_argument("--cluster", required=True,
                   help="sync target id — the kcp.dev/cluster label value "
                        "(reference: -cluster)")
    p.add_argument("--backend", choices=["tpu", "host"], default="tpu")
    p.add_argument("resources", nargs="+",
                   help="resource types to sync, e.g. deployments.apps")
    return p


async def run(args) -> None:
    from ..syncer import start_syncer

    upstream = RestClient(args.from_server, cluster=args.from_cluster)
    downstream = RestClient(args.to_server, cluster=args.to_cluster)
    syncer = await start_syncer(upstream, downstream, args.resources,
                                args.cluster, backend=args.backend)
    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    await syncer.stop()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
