"""Standalone deployment splitter.

The analog of the reference's cmd/deployment-splitter/main.go:17-33: run
only the Deployment split/aggregate controller against a kcp-tpu server —
root Deployments are split across registered Clusters with the batched
placement solver; leaf statuses aggregate back to the root.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys

from ..server.rest import MultiClusterRestClient
from .help import parser

DOC = """Split root Deployments across registered Clusters into labeled
leaf Deployments (replicas evenly partitioned by the batched placement
kernel) and aggregate leaf status back to the root."""


def build_parser():
    p = parser("deployment-splitter", DOC)
    p.add_argument("--server", default="http://127.0.0.1:6443",
                   help="kcp-tpu API server URL (reference: -kubeconfig)")
    p.add_argument("--backend", choices=["tpu", "host"], default="tpu")
    p.add_argument("--ca-file", default=None,
                   help="CA bundle for an https --server")
    return p


async def run(args) -> None:
    from ..reconcilers.deployment import DeploymentSplitter

    client = MultiClusterRestClient(args.server, ca_file=args.ca_file)
    splitter = DeploymentSplitter(client, backend=args.backend)
    await splitter.start()
    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    await splitter.stop()


def main(argv: list[str] | None = None) -> int:
    from . import apply_platform_env

    apply_platform_env()
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
