"""kcp_tpu.server — the minimal multi-tenant API server.

The analog of the reference's pkg/server (server.go:79-292) plus the
behavior it inherits from the un-vendored kcp-dev/kubernetes fork: a
Kubernetes-style REST+watch HTTP surface over the LogicalStore, with
per-tenant routing via the ``/clusters/<name>`` path prefix or the
``X-Kubernetes-Cluster`` header and wildcard ``*`` cross-tenant reads
(reference: pkg/server/server.go:164; docs/investigations/
logical-clusters.md:70-74).
"""

from .handler import RestHandler
from .httpd import HttpServer
from .rest import MultiClusterRestClient, RestClient, RestWatch
from .server import Config, Server

__all__ = [
    "Config",
    "HttpServer",
    "MultiClusterRestClient",
    "RestClient",
    "RestHandler",
    "RestWatch",
    "Server",
]
