"""REST request routing: Kubernetes-style paths over the LogicalStore.

Implements the HTTP surface of the reference's minimal apiserver
(reference: pkg/server/server.go:145 CreateServerChain serves the generic
control plane at :6443) with the fork's logical-cluster semantics:

- ``/clusters/<name>`` path prefix or ``X-Kubernetes-Cluster`` header
  selects the tenant; ``*`` reads across all tenants
  (reference: server.go:164; docs/investigations/logical-clusters.md:70-74)
- writes against the wildcard route to the logical cluster named in
  ``metadata.clusterName`` — the fork's multi-cluster write routing
  (reference call site: clientutils.EnableMultiCluster, server.go:230)
- discovery (``/api``, ``/apis``, per-group resource lists), CRUD,
  ``/status`` subresource, and ``?watch=true`` chunked event streams with
  ``labelSelector`` / ``resourceVersion`` parameters.
"""

from __future__ import annotations

import asyncio
import functools
import json
import os
import time

from .. import obs
from ..admission.chain import NOOP_TICKET
from ..apis.scheme import GVR, ResourceInfo, Scheme
from ..store.selectors import parse_selector
from ..store.store import INITIAL_EVENTS_END, WILDCARD, LogicalStore
from ..utils import errors
from ..utils.routing import resolve_write_cluster
from ..utils.trace import REGISTRY
from .httpd import FlushCoalescer, Request, Response, StreamResponse

DEFAULT_CLUSTER = "admin"
CLUSTER_HEADER = "x-kubernetes-cluster"


class _SlowWatcher(Exception):
    """A watch stream fell past KCP_WATCH_BUFFER_MAX on its socket: the
    coalescer refused further buffering and the producer must end the
    stream with a terminal typed 410 (the informer relists and resumes
    — bounded memory beats an unbounded goodbye)."""


def _status_body(code: int, reason: str, message: str) -> dict:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure" if code >= 400 else "Success",
        "reason": reason,
        "message": message,
        "code": code,
    }


def _error_response(err: errors.ApiError) -> Response:
    body = _status_body(err.code, err.reason, err.message)
    headers: dict[str, str] = {}
    retry_after = getattr(err, "retry_after", None)
    if retry_after is not None:
        # flow-control rejection (429): the pacing hint rides both the
        # HTTP header (for generic clients) and the Status details (for
        # RestClient, which only parses the body on watch streams)
        import math

        seconds = max(1, int(math.ceil(float(retry_after))))
        body["details"] = {"retryAfterSeconds": seconds}
        headers["Retry-After"] = str(seconds)
    resp = Response.of_json(body, err.code)
    resp.headers.update(headers)
    return resp


class RestHandler:
    """Routes parsed HTTP requests onto a LogicalStore + Scheme."""

    def __init__(self, store: LogicalStore, scheme: Scheme,
                 version_info: dict | None = None,
                 authenticator=None, authorizer=None,
                 admission="auto"):
        self.store = store
        self.scheme = scheme
        self.authenticator = authenticator
        self.authorizer = authorizer  # None = authz off (open prototype mode)
        # admission & flow control between authz and the store verbs
        # (admission/): "auto" builds the default chain (defaulting →
        # validation → quota, env-configured flow control) unless
        # KCP_ADMISSION=0; None disables; an AdmissionChain is used as-is
        if admission == "auto":
            from ..admission import build_chain

            admission = build_chain(store)
        self.admission = admission or None
        self.version_info = version_info or {"major": "0", "minor": "1",
                                             "gitVersion": "kcp-tpu-v0.1.0"}
        # /readyz gate: flipped by Server once post-start hooks complete
        # (reference: the apiserver's readiness reflects post-start hooks,
        # server.go:179-256)
        self.ready = False
        # external-storage frontends: every store verb is a blocking HTTP
        # round trip to the backend, so it must not run on the serving
        # loop (one slow backend call would freeze every request, watch
        # stream, and health probe). A small pool bounds concurrency;
        # in-process stores stay inline (in-memory, and the race guard
        # expects loop-thread affinity).
        self._remote = getattr(store, "is_remote", False)
        self._store_pool = None
        if self._remote:
            from concurrent.futures import ThreadPoolExecutor

            self._store_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="store-io")
        # encode-once serving (KCP_ENCODE_CACHE, in-process CoW stores
        # only): list responses splice cached item bytes, single GETs
        # splice the cached body, and the watch relay threads pre-encoded
        # event lines — remote-store frontends re-serialize what the
        # backend sent, so they keep the dict path.
        self._encode = (not self._remote
                        and bool(getattr(store, "encode_cache_enabled", False))
                        and callable(getattr(store, "encode_many", None))
                        and callable(getattr(store, "encode_events", None)))
        self._spans = callable(getattr(store, "list_encoded", None))
        self._enc_seconds = REGISTRY.histogram(
            "response_encode_seconds",
            "time serializing one list/get/watch-batch response body")
        # RV-keyed list-body cache: the store RV increments on every
        # mutation, so (query shape, rv) fully determines a list
        # response's bytes — informer relists and polling dashboards
        # repeat identical list queries against an unchanged store, and
        # those hits skip even the byte-splice. Small FIFO (bodies can
        # be tens of MB at 100k objects); bypassed while a KCP_FAULTS
        # schedule is active so encode.cache drops always reach the
        # per-record cache underneath.
        # entries are (rv, body spans, total bytes): the spans splice
        # straight into Response.spans so even a cache hit never pays a
        # whole-body join while the scatter wire path is on
        self._list_cache: dict[tuple, tuple[int, tuple[bytes, ...], int]] = {}
        self._list_cache_max = 8
        # HA replication (kcp_tpu/replication/): the Server wires these.
        # repl_hub — primary-side WAL shipper (feed + acks + fencing);
        # repl_applier — follower-side applier (replica/standby roles);
        # repl_role — what /replication/status reports;
        # repl_lag_max — replicas refuse reads 503 past this lag
        # (KCP_REPL_LAG_MAX; 0 = serve any staleness, RV-honestly).
        self.repl_hub = None
        self.repl_applier = None
        self.repl_role = "primary"
        self.repl_lag_max = 0
        # KEP-2340 consistent reads: replica-side RV-barrier telemetry
        self._consistent_waits = REGISTRY.counter(
            "consistent_read_waits_total",
            "replica reads that parked on the RV barrier because "
            "applied_rv was behind the required RV")
        self._consistent_timeouts = REGISTRY.counter(
            "consistent_read_timeouts_total",
            "RV-barrier reads that hit KCP_CONSISTENT_READ_TIMEOUT_MS "
            "and answered the typed 504 (callers fall back to the "
            "primary)")
        self._consistent_wait_seconds = REGISTRY.histogram(
            "consistent_read_wait_seconds",
            "time an RV-barrier read waited for this follower to apply "
            "its required RV")
        # group-commit admission batching: commit-window future -> the
        # enrolled writes' (quota reservation, after-hook) pairs; settled
        # in ONE ledger pass when the window resolves (_settle_adm_window)
        self._adm_windows: dict = {}
        # graceful drain (Server.drain): once set, every live watch
        # producer flushes its buffered events, sends a terminal
        # in-stream Status, and returns — the half of "no watcher is
        # abandoned mid-stream" that the HTTP layer cannot do alone
        self.draining = asyncio.Event()
        # epoch fence (POST /replication/fence): a fenced store can never
        # deliver another watch event, so live watch producers end with
        # the SAME terminal Status as drain (resumable from last_rv) and
        # consumers re-resolve onto the promoted primary instead of
        # idling on a sealed store forever. Separate from ``draining``
        # because a fenced server keeps serving: /replication/status must
        # answer probes/audits and writes must reach the store's own
        # fenced refusal (repl_fenced_writes_total)
        self.watch_fence = asyncio.Event()
        # watcher-scale serving (KCP_WATCH_COALESCE, default on): one
        # shared flush coalescer gathers every watch stream's encode-once
        # lines and writes each socket once per coalescing tick —
        # O(sockets) buffered writes of shared bytes per tick instead of
        # a write+drain round trip per watcher per event batch. =0 keeps
        # the per-batch send_raw_many path for A/B (bench.py --watchers).
        self._coalescer = None
        if os.environ.get("KCP_WATCH_COALESCE", "1").lower() not in (
                "0", "false", "off"):
            self._coalescer = FlushCoalescer(
                tick_s=float(os.environ.get("KCP_WATCH_FLUSH_MS", "2"))
                / 1000.0,
                buffer_max=int(os.environ.get(
                    "KCP_WATCH_BUFFER_MAX", str(2 * 1024 * 1024))))
        # per-server bookmark cadence (KCP_WATCH_BOOKMARK_S): how often
        # an idle stream that asked for bookmarks gets a progress marker
        # at the store RV — what keeps a quiet informer's resume point
        # inside the watch window across stream drops
        self._bookmark_every = float(
            os.environ.get("KCP_WATCH_BOOKMARK_S", "5"))
        # smart-client ring identity (Server wires these from
        # --shard-name/--ring-names): when set, a direct request that
        # stamps X-Kcp-Ring-Epoch is verified against HRW ownership — a
        # client holding a stale ring gets a typed 410 (refresh /ring)
        # instead of a silently-wrong shard's answer. Routed traffic
        # (no stamp) is untouched.
        self.shard_name = ""
        self.ring_names: tuple[str, ...] = ()
        self.ring_epoch = 0
        # per-cluster pending-migration overlay (cluster -> owning shard
        # NAME): while a cluster migrates, the router pins it to its old
        # owner and fans the pinned map out here (POST /ring) so direct
        # verification agrees with routing mid-move.
        self.ring_overrides: dict[str, str] = {}

    async def _st(self, fn, *args, **kwargs):
        """Run a store call; offloaded to the I/O pool for remote stores."""
        if self._store_pool is None:
            return fn(*args, **kwargs)
        return await asyncio.get_running_loop().run_in_executor(
            self._store_pool, functools.partial(fn, *args, **kwargs))

    def _forbidden(self, req, action: str) -> Response:
        user = self.authenticator.user_for(req.headers)
        return Response.of_json(
            _status_body(403, "Forbidden", f'user "{user}" cannot {action}'),
            403)

    def close(self) -> None:
        """Release handler resources (the store-I/O pool's threads)."""
        if self._store_pool is not None:
            self._store_pool.shutdown(wait=False, cancel_futures=True)

    async def _server_scope_allowed(self, req) -> bool:
        """True when the caller may read server-global (cross-tenant)
        state — /debug, /clusters, the RV in /version share this one
        gate. Always true in open mode; the authz check itself goes
        through :meth:`_st` because on a remote-store frontend the
        Authorizer reads roles/bindings through the remote store."""
        if self.authorizer is None:
            return True
        user = self.authenticator.user_for(req.headers)
        return await self._st(
            self.authorizer.allowed, user, WILDCARD, "get", "", "debug")

    # ------------------------------------------------------------- routing

    async def __call__(self, req: Request) -> Response | StreamResponse:
        """Serve one request under a trace context (kcp_tpu/obs/): the
        incoming ``traceparent`` is honored, otherwise a root is minted
        (head-sampled); the span records only when sampled — except
        SLO-breaching requests (> KCP_TRACE_SLO_MS), which force-record
        so a latency regression always comes with its own explanation.
        Under ``KCP_TRACE=0`` this wrapper is one attribute read."""
        tracer = obs.TRACER
        if not tracer.enabled:
            return await self._handle(req)
        tp = req.headers.get(obs.TRACEPARENT)
        if tp is None and not tracer.head_sampled():
            # the overwhelmingly common case — untraced arrival, coin
            # says no: one header probe, one coin draw, two clock reads;
            # the SLO check still upgrades a slow request afterwards
            t0 = time.time()
            resp = await self._handle(req)
            dur = time.time() - t0
            if dur >= tracer.slo_s:
                self._slo_span(None, req, resp, t0, dur)
            return resp
        ctx = tracer.from_headers(req.headers) if tp else \
            tracer.mint(sampled=True)
        if ctx is None or not ctx.sampled:
            # propagated-but-unsampled (or malformed) header: same
            # unsampled path, but an SLO breach keeps the caller's trace
            t0 = time.time()
            resp = await self._handle(req)
            dur = time.time() - t0
            if dur >= tracer.slo_s:
                self._slo_span(ctx, req, resp, t0, dur)
            return resp
        sub = tracer.child(ctx)
        token = obs.set_current(sub)
        t0 = time.time()
        status = 500
        try:
            resp = await self._handle(req)
            status = getattr(resp, "status", 200)
            return resp
        finally:
            obs.reset_current(token)
            dur = time.time() - t0
            attrs = {"method": req.method, "path": req.path,
                     "status": status}
            if dur >= tracer.slo_s:
                attrs["slo_breach"] = True
            obs.record_span("server.request", sub, ctx.span_id, t0,
                            dur, attrs)

    @staticmethod
    def _slo_span(ctx, req: Request, resp, t0: float, dur: float) -> None:
        """Force-record the serving span of an SLO-breaching request
        that head sampling skipped — a latency regression always ships
        with its own explanation."""
        tracer = obs.TRACER
        base = ctx or tracer.mint(sampled=False)
        if base is None:
            return
        obs.record_span(
            "server.request", tracer.child(base), base.span_id, t0, dur,
            {"method": req.method, "path": req.path,
             "status": getattr(resp, "status", 200), "slo_breach": True},
            force=True)

    async def _handle(self, req: Request) -> Response | StreamResponse:
        if self.draining.is_set():
            # graceful drain: in-flight requests were waited out BEFORE
            # the flag flipped; anything arriving now (a request that
            # raced the listener close on a kept-alive connection) must
            # not commit AFTER the watchers' final flush — refuse 503 so
            # the client retries against a live endpoint. Without this,
            # a write landing post-flush is a WAL record no stream ever
            # carried: the restarted server's history starts past it and
            # honest resumes answer 410 (a real lost-event window).
            return _error_response(errors.UnavailableError(
                "server is draining; retry against a live endpoint"))
        segs = [s for s in req.path.split("/") if s]
        cluster = req.headers.get(CLUSTER_HEADER, DEFAULT_CLUSTER)
        if len(segs) >= 2 and segs[0] == "clusters":
            cluster = segs[1]
            segs = segs[2:]
        if (self.shard_name and self.ring_names and cluster != WILDCARD
                and "x-kcp-ring-epoch" in req.headers):
            # a smart client came DIRECT with its ring stamp: verify HRW
            # ownership (names alone determine it — URLs never enter the
            # hash). A stale ring answers a typed 410 carrying OUR epoch;
            # the client re-fetches /ring and takes one router hop.
            from ..sharding.ring import owner_name

            owner = self.ring_overrides.get(cluster) or owner_name(
                self.ring_names, cluster)
            if owner != self.shard_name:
                resp = _error_response(errors.GoneError(
                    f"ring mismatch: cluster {cluster!r} is owned by "
                    f"shard {owner!r}, not {self.shard_name!r} — "
                    f"re-fetch /ring and retry"))
                resp.headers["X-Kcp-Ring-Epoch"] = str(self.ring_epoch)
                return resp
        if not segs:
            return Response.of_json({"paths": ["/api", "/apis", "/healthz", "/version"]})
        head = segs[0]
        if head == "healthz" or head == "livez":
            return Response(body=b"ok", content_type="text/plain")
        if head == "readyz":
            if self.ready:
                return Response(body=b"ok", content_type="text/plain")
            return Response(status=500, body=b"not ready", content_type="text/plain")
        if head == "version":
            # resourceVersion rides along so a storage-frontend peer
            # (store/remote.py) can probe the store's current RV with one
            # cheap GET instead of listing anything. The RV is global
            # (cross-tenant) state, so with authz on it is only included
            # for callers holding the same wildcard read /debug carries —
            # the version fields themselves stay public, as on the real
            # apiserver.
            body = dict(self.version_info)
            if await self._server_scope_allowed(req):
                try:
                    body["resourceVersion"] = str(
                        await self._st(lambda: self.store.resource_version))
                except RuntimeError:
                    # remote-store frontend whose backend withholds the RV
                    # (insufficient --store-token): the version fields
                    # stay public and the RV is simply omitted, exactly
                    # as the backend itself responds to that token. Peer
                    # RV probes still fail loudly (missing key).
                    pass
            return Response.of_json(body)
        if head == "clusters" and len(segs) == 1:
            # index of live logical clusters (the store's tenant set) —
            # used by wildcard single-object reads on storage frontends.
            # The tenant list is exactly what per-tenant RBAC is meant to
            # hide, so it is gated like /debug (server-global read).
            if not await self._server_scope_allowed(req):
                return self._forbidden(req, "list clusters")
            return Response.of_json(
                {"clusters": await self._st(self.store.clusters)})
        if head == "metrics":
            from ..utils.trace import REGISTRY

            return Response(body=REGISTRY.expose().encode("utf-8"),
                            content_type="text/plain; version=0.0.4")
        if head == "debug" and segs[1:] == ["profile"]:
            # the /debug/pprof analog (reference pkg/server/server.go:145
            # inherits it from the apiserver chain): sampling wall profile
            # + asyncio task dump + span histograms. Server-global, so
            # with authz on it is gated like cross-tenant reads (root
            # cluster-admin), matching pprof-on-the-secure-port semantics.
            if not await self._server_scope_allowed(req):
                return self._forbidden(req, "read /debug/profile")
            from ..utils.trace import sample_profile

            try:
                seconds = float(req.param("seconds", "2.0"))
            except (TypeError, ValueError):
                seconds = 2.0
            return Response.of_json(await sample_profile(seconds))
        if head == "debug" and segs[1:] == ["trace"]:
            # distributed-trace queries (?id= / ?slowest=N) serve this
            # process's span ring buffer; without either param the legacy
            # on-demand XLA/device trace (xprof) is preserved below.
            # Same server-global gate either way.
            if not await self._server_scope_allowed(req):
                return self._forbidden(req, "trace")
            if req.param("id") or req.param("slowest"):
                return self._trace_query(req)
            import tempfile

            from ..utils.trace import device_trace

            try:
                seconds = min(float(req.param("seconds", "2.0")), 30.0)
            except (TypeError, ValueError):
                seconds = 2.0
            log_dir = req.param("dir") or tempfile.mkdtemp(
                prefix="kcp-device-trace-")
            with device_trace(log_dir) as started:
                await asyncio.sleep(seconds)
            return Response.of_json({
                "dir": log_dir, "seconds": seconds,
                "started": bool(started),
                "hint": "view with xprof/tensorboard --logdir",
            })
        if head == "replication":
            return await self._replication(req, segs[1:])
        if head == "migration":
            return await self._migration(req, segs[1:])
        if head == "ring" and req.method == "POST" and self.shard_name:
            return await self._ring_install(req)
        if head == "api":
            return await self._route_group(req, cluster, group="", segs=segs[1:])
        if head == "apis":
            return await self._route_apis(req, cluster, segs[1:])
        if head == "openapi" and segs[1:] == ["v2"]:
            # the document discloses the cluster's CRD schemas — gate it
            # exactly like listing CRDs in that cluster
            if self.authorizer is not None:
                user = self.authenticator.user_for(req.headers)
                if not await self._st(
                        self.authorizer.allowed, user, cluster, "list",
                        "apiextensions.k8s.io", "customresourcedefinitions"):
                    return Response.of_json(
                        _status_body(403, "Forbidden",
                                     f'user "{user}" cannot read the openapi '
                                     f'document of logical cluster "{cluster}"'),
                        403)
            return Response.of_json(await self._st(self._openapi_v2, cluster))
        return _error_response(errors.NotFoundError(f"unknown path {req.path}"))

    async def _route_apis(self, req: Request, cluster: str, segs: list[str]):
        if not segs:
            groups = []
            for group, versions in sorted(self.scheme.group_versions().items()):
                if not group:
                    continue
                vs = sorted(versions)
                groups.append({
                    "name": group,
                    "versions": [{"groupVersion": f"{group}/{v}", "version": v} for v in vs],
                    "preferredVersion": {"groupVersion": f"{group}/{vs[0]}", "version": vs[0]},
                })
            return Response.of_json({"kind": "APIGroupList", "apiVersion": "v1",
                                     "groups": groups})
        group, segs = segs[0], segs[1:]
        return await self._route_group(req, cluster, group, segs)

    async def _route_group(self, req: Request, cluster: str, group: str, segs: list[str]):
        if not segs:
            if group == "":
                return Response.of_json({"kind": "APIVersions", "versions": ["v1"]})
            versions = sorted(self.scheme.group_versions().get(group, ()))
            if not versions:
                return _error_response(errors.NotFoundError(f"unknown group {group}"))
            return Response.of_json({
                "kind": "APIGroup", "apiVersion": "v1", "name": group,
                "versions": [{"groupVersion": f"{group}/{v}", "version": v} for v in versions],
            })
        version, segs = segs[0], segs[1:]
        if not segs:
            return self._discovery(group, version)

        # path shapes (after group/version):
        #   <resource>[/<name>[/status]]                      cluster-scoped
        #   namespaces/<ns>/<resource>[/<name>[/status]]      namespaced
        namespace = ""
        if segs[0] == "namespaces" and len(segs) >= 3:
            namespace = segs[1]
            segs = segs[2:]
        resource, segs = segs[0], segs[1:]
        name = segs[0] if segs else None
        subresource = segs[1] if len(segs) > 1 else None
        if len(segs) > 2 or subresource not in (None, "status"):
            return _error_response(errors.NotFoundError(f"unknown path {req.path}"))

        info = self._resolve(group, version, resource)
        if info is None:
            return _error_response(
                errors.NotFoundError(f"the server could not find the requested "
                                     f"resource {resource} in {group}/{version}"))
        if self.authorizer is not None:
            from .authz import verb_for

            user = self.authenticator.user_for(req.headers)
            # ?watch=true is only served as a watch on collection GETs
            # (named GETs fall through to a plain get below) — authorize
            # the operation that will actually run
            is_watch = name is None and req.param("watch") in ("true", "1")
            verb = verb_for(req.method, name is not None, is_watch)
            if not await self._st(self.authorizer.allowed, user, cluster,
                                  verb, group, resource):
                return Response.of_json(
                    _status_body(403, "Forbidden",
                                 f'user "{user}" cannot {verb} {resource} '
                                 f'in logical cluster "{cluster}"'), 403)
            if (verb in ("create", "update", "patch")
                    and group == "rbac.authorization.k8s.io"
                    and resource in ("clusterroles", "clusterrolebindings")):
                # RBAC writes additionally pass Kubernetes' escalation
                # check: you cannot grant what you do not hold
                try:
                    body = req.json()
                except ValueError:
                    body = None
                if not isinstance(body, dict):
                    # malformed bodies fall through to _serve_resource's
                    # 400; the check itself must not crash on them
                    body = None
                denial = await self._st(
                    self.authorizer.escalation_denied,
                    user, cluster, resource, body)
                if denial:
                    return Response.of_json(
                        _status_body(403, "Forbidden", denial), 403)
        try:
            return await self._serve_resource(req, cluster, info, namespace, name, subresource)
        except errors.ApiError as e:
            return _error_response(e)

    @staticmethod
    def _trace_query(req: Request) -> Response:
        """Serve this process's span ring buffer: ``?id=<trace>`` returns
        one trace's spans, ``?slowest=N`` the N slowest buffered traces.
        The router scatter-gathers this endpoint across shards to
        assemble cross-process trees."""
        tracer = obs.TRACER
        tid = req.param("id")
        if tid:
            return Response.of_json({
                "id": tid, "proc": tracer.proc, "spans": tracer.get(tid)})
        try:
            n = max(1, min(int(req.param("slowest") or "3"), 32))
        except ValueError:
            n = 3
        return Response.of_json({
            "proc": tracer.proc, "traces": tracer.slowest(n)})

    def _openapi_v2(self, cluster: str) -> dict:
        """Serve the cluster's swagger document: an attached
        ``store.openapi_doc`` wins (the fake physical cluster's discovery
        fixture); otherwise it is synthesized from the cluster's CRDs
        (:func:`kcp_tpu.crdpuller.openapi.doc_from_crds`)."""
        from ..apis import crd as crdapi
        from ..crdpuller.openapi import doc_from_crds

        if self.store.openapi_doc is not None:
            return self.store.openapi_doc
        try:
            crds, _ = self.store.list(crdapi.CRDS.storage_name, cluster)
        except errors.ApiError:
            crds = []
        return doc_from_crds(crds)

    def _resolve(self, group: str, version: str, resource: str) -> ResourceInfo | None:
        info = self.scheme.by_resource(GVR(group, version, resource).storage_name)
        if info is not None and info.gvr.version != version:
            return None
        return info

    def _discovery(self, group: str, version: str) -> Response:
        resources = []
        for info in self.scheme.all():
            if info.gvr.group != group or info.gvr.version != version:
                continue
            resources.append({
                "name": info.gvr.resource, "singularName": info.singular,
                "kind": info.kind, "namespaced": info.namespaced,
                "verbs": ["create", "delete", "get", "list", "update", "watch"],
            })
            if info.has_status:
                resources.append({
                    "name": f"{info.gvr.resource}/status", "singularName": "",
                    "kind": info.kind, "namespaced": info.namespaced,
                    "verbs": ["get", "update"],
                })
        if not resources:
            return _error_response(errors.NotFoundError(f"unknown group/version {group}/{version}"))
        gv = f"{group}/{version}" if group else version
        return Response.of_json({"kind": "APIResourceList", "apiVersion": "v1",
                                 "groupVersion": gv, "resources": resources})

    # ---------------------------------------------------------- resources

    async def _serve_resource(self, req: Request, cluster: str, info: ResourceInfo,
                              namespace: str, name: str | None, subresource: str | None):
        res = info.gvr.storage_name
        gv = f"{info.gvr.group}/{info.gvr.version}" if info.gvr.group else info.gvr.version

        if subresource == "status" and req.method not in ("GET", "PUT"):
            # discovery advertises get+update only; a DELETE here must not
            # silently remove the whole object
            raise errors.BadRequestError(
                "the status subresource supports get and update only")

        if req.method == "GET":
            from ..apis.printers import render_table, wants_table

            self._check_replica_lag()
            await self._consistent_read_gate(
                req, watch=(name is None
                            and req.param("watch") in ("true", "1")))
            as_table = wants_table(req.headers.get("accept", ""))
            if name is None:
                if req.param("watch") in ("true", "1"):
                    return self._watch(req, cluster, res, namespace or None)
                selector = parse_selector(req.param("labelSelector"))
                limit_s = req.param("limit")
                cont = req.param("continue")
                if ((limit_s or cont) and not as_table
                        and hasattr(self.store, "list_page")):
                    try:
                        limit = int(limit_s) if limit_s else 0
                    except ValueError:
                        raise errors.BadRequestError(
                            f"malformed limit {limit_s!r}") from None
                    if limit < 0:
                        raise errors.BadRequestError("limit must be >= 0")
                    return await self._list_page(
                        req, cluster, res, namespace, selector, info, gv,
                        limit, cont or None)
                if self._encode and not as_table:
                    return await self._list_encoded(
                        req, cluster, res, namespace, selector, info, gv)
                items, rv = await self._st(
                    self.store.list, res, cluster, namespace or None, selector)
                if as_table:  # kubectl get: server-side printer columns
                    return Response.of_json(render_table(res, items, rv))
                t0 = time.perf_counter()
                resp = Response.of_json({
                    "kind": info.list_kind, "apiVersion": gv,
                    "metadata": {"resourceVersion": str(rv)},
                    "items": items,
                })
                self._enc_seconds.observe(time.perf_counter() - t0)
                return resp
            target = await self._read_cluster(cluster, res, name, namespace)
            if self._encode and not as_table:
                raw = self._get_encoded(res, target, name, namespace)
                if raw is not None:
                    return Response(body=raw)
            obj = await self._st(self.store.get, res, target, name, namespace)
            # no table transform for the status subresource (matches the
            # real apiserver: table rendering applies to objects, not
            # subresources)
            if as_table and subresource is None:
                return Response.of_json(render_table(res, [obj]))
            return Response.of_json(self._stamp(obj, info, gv))

        if req.method == "POST" and name is None:
            obj = self._body_object(req)
            target = resolve_write_cluster(cluster, obj, errors.BadRequestError)
            # admission inline (reads never touch it): admit_nowait only
            # hands back a coroutine when flow control parks the request,
            # so the uncontended write path stays synchronous
            adm = self.admission
            if adm is None:
                ticket = NOOP_TICKET
            else:
                with obs.span("admission.admit", verb="create"):
                    got = adm.admit_nowait("create", res, target, namespace,
                                           obj)
                    ticket = got if hasattr(got, "ok") else await got
            try:
                created = await self._st(
                    self.store.create, res, target, obj, namespace)
            except BaseException:
                ticket.fail()
                raise
            await self._finish_write(ticket)
            return self._rv_stamped(
                Response.of_json(self._stamp(created, info, gv), 201),
                (created.get("metadata") or {}).get("resourceVersion"))

        if req.method == "PUT" and name is not None:
            obj = self._body_object(req)
            body_name = obj.setdefault("metadata", {}).setdefault("name", name)
            if body_name != name:
                raise errors.BadRequestError(
                    f"name in URL ({name}) does not match name in object ({body_name})")
            target = resolve_write_cluster(cluster, obj, errors.BadRequestError)
            adm = self.admission
            if adm is None:
                ticket = NOOP_TICKET
            else:
                with obs.span("admission.admit", verb="update"):
                    got = adm.admit_nowait("update", res, target, namespace,
                                           obj)
                    ticket = got if hasattr(got, "ok") else await got
            try:
                if subresource == "status":
                    updated = await self._st(
                        self.store.update_status, res, target, obj, namespace)
                else:
                    updated = await self._st(
                        self.store.update, res, target, obj, namespace)
            except BaseException:
                ticket.fail()
                raise
            await self._finish_write(ticket)
            return self._rv_stamped(
                Response.of_json(self._stamp(updated, info, gv)),
                (updated.get("metadata") or {}).get("resourceVersion"))

        if req.method == "DELETE" and name is not None:
            target = await self._read_cluster(cluster, res, name, namespace)
            adm = self.admission
            if adm is None:
                ticket = NOOP_TICKET
            else:
                with obs.span("admission.admit", verb="delete"):
                    got = adm.admit_nowait("delete", res, target, namespace,
                                           None)
                    ticket = got if hasattr(got, "ok") else await got
            try:
                await self._st(self.store.delete, res, target, name, namespace)
            except BaseException:
                ticket.fail()
                raise
            await self._finish_write(ticket)
            # a delete's Status body carries no RV, but session
            # read-your-writes needs a floor covering it: stamp the
            # store RV (>= the delete's own RV) as a response header
            rv = (0 if self._remote
                  else getattr(self.store, "resource_version", 0))
            return self._rv_stamped(
                Response.of_json(_status_body(
                    200, "Deleted", f"{res} {name} deleted")), rv)

        raise errors.BadRequestError(f"unsupported method {req.method} for {req.path}")

    @staticmethod
    def _rv_stamped(resp: Response, rv) -> Response:
        """Mirror a write's committed RV as ``X-Kcp-Rv`` so clients can
        raise their session read-your-writes floor without parsing the
        body (delete acks are Status objects with no RV at all)."""
        if rv:
            resp.headers["X-Kcp-Rv"] = str(rv)
        return resp

    @staticmethod
    def _body_object(req: Request) -> dict:
        try:
            obj = req.json()
        except ValueError as e:
            raise errors.BadRequestError(f"malformed JSON body: {e}") from e
        if not isinstance(obj, dict):
            raise errors.BadRequestError("body must be a JSON object")
        return obj

    def _stamp(self, obj: dict, info: ResourceInfo, gv: str) -> dict:
        obj.setdefault("kind", info.kind)
        obj.setdefault("apiVersion", gv)
        return obj

    async def _list_encoded(self, req: Request, cluster: str, res: str,
                            namespace: str, selector, info: ResourceInfo,
                            gv: str) -> Response:
        """Encode-once list serving: (1) an RV-keyed body cache answers
        repeated identical queries against an unchanged store without
        touching the items at all; (2) unselected lists assemble from
        the store's per-bucket span caches (no global sort, no per-item
        probe); (3) selector lists byte-splice the per-snapshot cached
        bytes. All three are byte-identical to dumping the full dict."""
        from .. import faults as _faults
        from ..analysis import sanitize as _san

        # bypassed while faults are active (encode.cache drops must
        # reach the per-record cache) and under the sanitizer (every hit
        # must flow through the verifying per-record paths)
        cacheable = (_faults._ACTIVE is None and _faults._ENV_CHECKED
                     and not _san.enabled())
        ck = (res, cluster, namespace, req.param("labelSelector") or "", gv)
        if cacheable:
            ent = self._list_cache.get(ck)
            if ent is not None and ent[0] == self.store.resource_version:
                REGISTRY.counter("encode_cache_hits_total").inc()
                REGISTRY.counter(
                    "encode_cache_bytes_shared_total").inc(ent[2])
                return Response(spans=list(ent[1]))
        t0 = time.perf_counter()
        if selector.empty and self._spans:
            spans, rv = await self._st(
                self.store.list_encoded, res, cluster, namespace or None)
        else:
            items, rv = await self._st(
                self.store.list, res, cluster, namespace or None, selector)
            spans = self.store.encode_many(items)
        # byte-splice: the envelope is dumped once with an empty items
        # array, then the item/span bytes are spliced in place of the
        # final `]}` — byte-identical to dumping the full dict, without
        # re-serializing 100k objects per request. The parts list IS the
        # response body (Response.spans): the wire layer writes the
        # spans scatter-style, so at 100k objects the tens-of-MB body is
        # never materialized as one joined copy at all
        # (KCP_WIRE_SCATTER; =0 restores the single join for A/B)
        head = json.dumps({
            "kind": info.list_kind, "apiVersion": gv,
            "metadata": {"resourceVersion": str(rv)},
            "items": [],
        }).encode()
        parts = [head[:-2]]
        for i, span in enumerate(spans):
            if i:
                parts.append(b", ")
            parts.append(span)
        parts.append(b"]}")
        total = sum(len(p) for p in parts)
        self._enc_seconds.observe(time.perf_counter() - t0)
        if cacheable:
            if (len(self._list_cache) >= self._list_cache_max
                    and ck not in self._list_cache):
                self._list_cache.pop(next(iter(self._list_cache)))
            self._list_cache[ck] = (rv, tuple(parts), total)
        return Response(spans=parts)

    async def _list_page(self, req: Request, cluster: str, res: str,
                         namespace: str, selector, info: ResourceInfo,
                         gv: str, limit: int, cont: str | None) -> Response:
        """KEP-365 chunked list serving: one RV-pinned page per request.

        Pages skip the RV-keyed whole-body cache (each page is its own
        body) but ride the same span-splice envelope as
        :meth:`_list_encoded` — a page is assembled from bucket-span
        slices, never a whole-body join. ``metadata`` keeps
        ``resourceVersion`` first so the router's vector-RV rewrite and
        continue-token splice anchor on it. A continue token the store's
        watch window no longer covers raises typed ``GoneError`` →
        HTTP 410, and the client restarts its chunked list."""
        t0 = time.perf_counter()
        if self._encode and selector.empty and self._spans:
            spans, rv, nxt = await self._st(
                self.store.list_encoded_page, res, cluster,
                namespace or None, limit, cont)
        else:
            items, rv, nxt = await self._st(
                self.store.list_page, res, cluster, namespace or None,
                selector, limit, cont)
            if not self._encode:
                meta: dict = {"resourceVersion": str(rv)}
                if nxt:
                    meta["continue"] = nxt
                resp = Response.of_json({
                    "kind": info.list_kind, "apiVersion": gv,
                    "metadata": meta, "items": items,
                })
                self._enc_seconds.observe(time.perf_counter() - t0)
                return resp
            spans = self.store.encode_many(items) if items else []
        meta = {"resourceVersion": str(rv)}
        if nxt:
            meta["continue"] = nxt
        head = json.dumps({
            "kind": info.list_kind, "apiVersion": gv,
            "metadata": meta, "items": [],
        }).encode()
        parts = [head[:-2]]
        for i, span in enumerate(spans):
            if i:
                parts.append(b", ")
            parts.append(span)
        parts.append(b"]}")
        self._enc_seconds.observe(time.perf_counter() - t0)
        return Response(spans=parts)

    def _get_encoded(self, res: str, cluster: str, name: str,
                     namespace: str) -> bytes | None:
        """Cached body for a single-object GET (encode-once: no deepcopy,
        no dumps on a warm snapshot). None when :meth:`_stamp` would have
        to add kind/apiVersion defaults — that rare shape takes the dict
        path so the wire stays byte-identical either way. In-process
        stores only (``self._encode``), so this runs inline."""
        snap = self.store.get_snapshot(res, cluster, name, namespace)
        if "kind" not in snap or "apiVersion" not in snap:
            return None
        t0 = time.perf_counter()
        raw = self.store.encode_obj(snap)
        self._enc_seconds.observe(time.perf_counter() - t0)
        return raw

    async def _read_cluster(self, cluster: str, res: str, name: str,
                            namespace: str) -> str:
        """Wildcard single-object reads scan tenants for the unique owner."""
        if cluster != WILDCARD:
            return cluster
        if self._remote:
            # storage frontend: the backend's own handler resolves '*'
            # (this same scan, against its in-memory index) — forwarding
            # the wildcard costs one round trip instead of tenants+1
            return cluster
        if hasattr(self.store, "locate"):
            # index-driven: only clusters holding the resource are probed
            matches = self.store.locate(res, name, namespace)
        else:
            matches = [c for c in self.store.clusters()
                       if self._exists(res, c, name, namespace)]
        if len(matches) == 1:
            return matches[0]
        if not matches:
            raise errors.NotFoundError(f"{res} {namespace}/{name} not found in any cluster")
        raise errors.BadRequestError(
            f"{res} {namespace}/{name} is ambiguous across clusters {matches}")

    def _exists(self, res: str, cluster: str, name: str, namespace: str) -> bool:
        try:
            self.store.get(res, cluster, name, namespace)
            return True
        except errors.NotFoundError:
            return False

    # -------------------------------------------------------- replication

    async def _replication(self, req: Request, segs: list[str]):
        """The WAL-shipping surface (kcp_tpu/replication/):

        - ``GET  /replication/wal``    chunked record feed (followers)
        - ``GET  /replication/status`` role/epoch/applied-RV/lag probe
        - ``POST /replication/ack``    standby applied-RV report
        - ``POST /replication/fence``  epoch fence (promotion kill switch)

        The feed carries every tenant's objects and the fence can stop
        a primary cold, so everything but ``status`` is gated like the
        other server-global surfaces (/debug, /clusters).
        """
        if segs == ["status"] and req.method == "GET":
            st = self.store
            body = {
                "role": self.repl_role,
                "epoch": getattr(st, "epoch", 0),
                "applied_rv": getattr(st, "resource_version", 0),
                "read_only": getattr(st, "read_only", None),
                "fenced": bool(getattr(st, "fenced", False)),
            }
            ap = self.repl_applier
            if ap is not None:
                body["lag_records"] = ap.lag_records
                body["frontier_rv"] = ap.frontier_rv
                body["apply_rate"] = round(ap.apply_rate, 3)
                body["connected"] = ap.connected
                body["primary"] = ap.primary_url
                body["primary_candidates"] = list(ap.candidates)
            if self.repl_hub is not None:
                body["subscribers"] = len(self.repl_hub._subs)
            return Response.of_json(body)
        if not await self._server_scope_allowed(req):
            user = (self.authenticator.user_for(req.headers)
                    if self.authenticator else "anonymous")
            return Response.of_json(
                _status_body(403, "Forbidden",
                             f'user "{user}" cannot access replication'),
                403)
        if self.repl_hub is None:
            return _error_response(errors.NotFoundError(
                "no replication hub on this server (routers and "
                "remote-store frontends do not ship a WAL)"))
        if segs == ["wal"] and req.method == "GET":
            try:
                since_rv = int(req.param("sinceRV", "0") or "0")
                sub_epoch = int(req.param("epoch", "0") or "0")
            except ValueError as e:
                raise errors.BadRequestError(
                    f"malformed replication params: {e}") from e
            role = req.param("role", "replica")
            if role not in ("replica", "standby", "migration"):
                raise errors.BadRequestError(
                    f"unknown replication role {role!r}")
            # migration transport (sharding/migrate.py): one cluster's
            # post-fence snapshot + BARRIER, nothing else — the same
            # feed, filtered
            mig_cluster = req.param("cluster") or None
            hub = self.repl_hub

            async def produce(stream: StreamResponse) -> None:
                try:
                    await hub.serve_feed(stream, since_rv, sub_epoch,
                                         role, mig_cluster)
                except errors.ApiError as e:
                    await stream.send_json({
                        "type": "ERROR",
                        "object": _status_body(e.code, e.reason, e.message)})

            return StreamResponse(produce)
        if segs == ["ack"] and req.method == "POST":
            body = self._body_object(req)
            self.repl_hub.ack(int(body.get("sub", 0)),
                              int(body.get("rv", 0)))
            return Response.of_json(_status_body(200, "OK", "acked"))
        if segs == ["fence"] and req.method == "POST":
            body = self._body_object(req)
            epoch = int(body.get("epoch", 0))
            if epoch < self.store.epoch:
                # a stale fence (e.g. from a promotion that itself got
                # superseded) must not stick: epochs only move forward
                raise errors.ConflictError(
                    f"fence epoch {epoch} is older than this store's "
                    f"epoch {self.store.epoch}")
            if epoch > self.store.epoch:
                self.store.fence(epoch)
                # flush + terminate every live watch stream: an open
                # watch on a fenced store would otherwise idle forever
                # (no writes can commit here again), never seeing the
                # promoted primary's events
                self.watch_fence.set()
            # equal epoch: idempotent retry of an applied fence (or a
            # no-op against the current epoch's own primary)
            return Response.of_json(_status_body(
                200, "OK",
                f"epoch {self.store.epoch}"
                + (" (fenced)" if self.store.fenced else "")))
        return _error_response(
            errors.NotFoundError(f"unknown path {req.path}"))

    # --------------------------------------------------------- migration

    async def _ring_install(self, req: Request) -> Response:
        """Shard-side ring identity update (``POST /ring``): the router
        fans the grown/shrunk ring (names, epoch, pending-migration
        overrides) out to every member on each epoch bump, so direct
        smart-client verification keeps agreeing with routing. The
        epoch never rewinds (a late fan-out from a superseded publish
        must not reinstate a stale ring)."""
        if not await self._server_scope_allowed(req):
            return self._forbidden(req, "update the shard ring")
        body = self._body_object(req)
        try:
            epoch = int(body.get("epoch", 0))
            names = tuple(str(n) for n in (body.get("names") or ()))
            overrides = {str(c): str(n) for c, n in
                         (body.get("overrides") or {}).items()}
        except (TypeError, ValueError, AttributeError) as e:
            raise errors.BadRequestError(
                f"malformed ring document: {e}") from e
        if not names or self.shard_name not in names:
            raise errors.BadRequestError(
                f"ring names {list(names)} must include this shard "
                f"({self.shard_name!r})")
        if epoch < self.ring_epoch:
            raise errors.ConflictError(
                f"ring epoch {epoch} is older than this shard's "
                f"{self.ring_epoch}; ring epochs never rewind")
        self.ring_names = names
        self.ring_epoch = epoch
        self.ring_overrides = overrides
        return Response.of_json(_status_body(
            200, "OK", f"ring installed: epoch {epoch}, "
            f"{len(names)} shards, {len(overrides)} pending migrations"))

    async def _migration(self, req: Request, segs: list[str]):
        """The live-migration control surface (sharding/migrate.py):

        - ``POST /migration/fence``   {cluster} on the SOURCE — refuse
          further writes to the cluster, return its cutover RV
        - ``POST /migration/unfence`` {cluster} — abort rollback
        - ``POST /migration/ingest``  ndjson WAL-shaped records on the
          TARGET — apply with fresh local RVs
        - ``POST /migration/finish``  {cluster, source_rv} on the TARGET
          — advance the RV counter past the source's and set the
          cluster's resume floor
        - ``POST /migration/purge``   {cluster} on the SOURCE — evict
          the cluster's watches (typed 410) and drop its objects with
          no watch events

        All of it moves tenant data across trust boundaries, so every
        verb is gated like the other server-global surfaces."""
        if req.method != "POST":
            return _error_response(
                errors.BadRequestError("migration endpoints are POST-only"))
        if not await self._server_scope_allowed(req):
            user = (self.authenticator.user_for(req.headers)
                    if self.authenticator else "anonymous")
            return Response.of_json(
                _status_body(403, "Forbidden",
                             f'user "{user}" cannot access migration'),
                403)
        st = self.store
        if not hasattr(st, "fence_cluster"):
            return _error_response(errors.NotFoundError(
                "no local store on this server (routers and remote-store "
                "frontends do not hold cluster data)"))
        if segs == ["ingest"]:
            applied = 0
            last_rv = None
            for line in (req.body or b"").splitlines():
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    raise errors.BadRequestError(
                        f"malformed migration record: {e}") from e
                rv = st.apply_migrated(rec)
                if rv is not None:
                    applied += 1
                    last_rv = rv
            return Response.of_json({"applied": applied, "rv": last_rv})
        body = self._body_object(req)
        cluster = body.get("cluster")
        if not cluster or not isinstance(cluster, str):
            raise errors.BadRequestError(
                "migration request needs a cluster name")
        if segs == ["fence"]:
            return Response.of_json(
                {"cluster": cluster, "cutover_rv": st.fence_cluster(cluster)})
        if segs == ["unfence"]:
            st.unfence_cluster(cluster)
            return Response.of_json(_status_body(
                200, "OK", f"cluster {cluster} unfenced"))
        if segs == ["finish"]:
            floor = st.finish_migration(cluster,
                                        int(body.get("source_rv", 0)))
            return Response.of_json({"cluster": cluster, "floor_rv": floor})
        if segs == ["purge"]:
            return Response.of_json(
                {"cluster": cluster, "purged": st.purge_cluster(cluster)})
        return _error_response(
            errors.NotFoundError(f"unknown path {req.path}"))

    async def _finish_write(self, ticket) -> None:
        """Release one write's HTTP ack: durability barrier first (the
        commit window's shared WAL sync — a window that dies pre-sync
        fails every writer typed and acks none), then the semi-sync
        standby wait at the window's high RV (one ack releases every
        writer of the window). The admission ticket settles with the
        same cadence: serial writes settle inline; windowed writes free
        their flow slot immediately but batch the quota reserve→commit
        into ONE ledger pass per window (admission/quota.settle_batch).
        """
        st = self.store
        # lazy on rv: remote-store frontends price resource_version as a
        # backend round trip, and they have neither windows nor a hub
        wait = getattr(st, "commit_durable", None)
        aw = wait() if wait is not None else None
        if aw is None:
            ticket.ok()
            rv = None
        else:
            self._enroll_ticket(aw, ticket)
            rv = await aw  # window high RV; typed 503 on a failed sync
        await self._repl_wait(rv)

    def _enroll_ticket(self, fut, ticket) -> None:
        """Park one write's admission obligations on its commit window:
        the flow slot frees NOW (window linger must not throttle
        concurrency), the quota reservation + after-hooks settle once
        per window when the shared future resolves."""
        split = getattr(ticket, "split_for_window", None)
        if split is None:
            ticket.ok()  # foreign ticket shape: settle inline
            return
        reservation, after = split()
        if reservation is None and after is None:
            return
        batch = self._adm_windows.get(fut)
        if batch is None:
            batch = self._adm_windows[fut] = []
            fut.add_done_callback(self._settle_adm_window)
        batch.append((reservation, after))

    def _settle_adm_window(self, fut) -> None:
        """One commit window resolved: settle every enrolled write's
        quota reservation in one batched ledger pass (commit on a
        durable window, rollback on a failed sync — 'commit none'
        applies to the ledger too) and fire the after-hooks."""
        batch = self._adm_windows.pop(fut, None)
        if not batch:
            return
        from ..admission.quota import settle_batch

        ok = not fut.cancelled() and fut.exception() is None
        settle_batch([r for r, _ in batch], rollback=not ok)
        if ok:
            for _, after in batch:
                if after is not None:
                    after()

    async def _repl_wait(self, rv: int | None = None) -> None:
        """Semi-sync commit: with a standby attached, a write is only
        acknowledged once the standby has applied ``rv`` (the write's
        own RV, or its commit window's high RV so the whole window rides
        one ack) — the property the kill-the-primary drill measures as
        zero acknowledged-write loss. No standby, no wait (async
        replication)."""
        hub = self.repl_hub
        if hub is not None and hub.has_sync_subscribers:
            with obs.span("repl.ack"):
                await hub.wait_committed(
                    rv or self.store.resource_version)

    def _check_replica_lag(self) -> None:
        """Reads on a replica past KCP_REPL_LAG_MAX refuse 503 — for
        consumers that prefer unavailability over staleness; the
        default (0) serves any staleness RV-honestly. The refusal
        carries a computed Retry-After (current lag / recent apply
        rate) so informers back off exactly as long as catch-up needs
        instead of a generic jittered retry."""
        ap = self.repl_applier
        if (self.repl_lag_max and ap is not None
                and ap.lag_records > self.repl_lag_max):
            err = errors.UnavailableError(
                f"replica lag {ap.lag_records} records exceeds "
                f"KCP_REPL_LAG_MAX={self.repl_lag_max}; read the primary")
            rate = getattr(ap, "apply_rate", 0.0)
            err.retry_after = (min(30.0, max(1.0, ap.lag_records / rate))
                               if rate > 0 else 1.0)
            raise err

    @staticmethod
    def _consistent_timeout_s() -> float:
        try:
            ms = float(os.environ.get(
                "KCP_CONSISTENT_READ_TIMEOUT_MS", "2000") or 0)
        except ValueError:
            ms = 2000.0
        return max(0.0, ms / 1000.0)

    async def _consistent_read_gate(self, req: Request,
                                    watch: bool = False) -> None:
        """KEP-2340 RV-barrier for reads on a follower: a read carrying
        a required RV (``X-Kcp-Min-Rv: <rv>``, ``X-Kcp-Min-Rv:
        consistent`` resolved against the progress-notify frontier, an
        RV-pinned continue token, or a watch resume RV) parks on the
        applier's bounded waiter until ``applied_rv >= required``, then
        serves from the local store through the encode-once path —
        byte-identical to the primary at that RV. Timeout answers the
        typed 504 (:class:`~kcp_tpu.utils.errors.FrontierTimeoutError`)
        and the caller falls back to the primary; a timed-out watch
        resume instead falls through to the store's own
        ``reject_future_rv`` answer (typed 410 → the client re-lists).
        No-op on a primary: it IS the frontier."""
        ap = self.repl_applier
        if ap is None or ap.promoted:
            return
        raw = (req.headers.get("x-kcp-min-rv") or "").strip()
        required = 0
        if raw:
            if raw.lower() == "consistent":
                # one cheap frontier probe: the progress-notify stream
                # keeps last_seen_rv fresh even on a quiet feed
                required = ap.frontier_rv
            else:
                try:
                    required = int(raw)
                except ValueError:
                    raise errors.BadRequestError(
                        f"malformed X-Kcp-Min-Rv {raw!r}") from None
        cont = req.param("continue")
        if cont:
            from ..store.store import decode_continue

            try:
                required = max(required, decode_continue(cont)[0])
            except ValueError:
                pass  # the page path answers the typed 410
        since = req.param("resourceVersion")
        if since:
            # a watch resume RV or an RV-pinned list: both mean "the
            # client has seen this RV" — the same barrier applies
            try:
                required = max(required, int(since))
            except ValueError:
                pass  # _watch raises the typed 400; lists ignore it
        if required <= self.store.resource_version:
            return
        timeout_s = self._consistent_timeout_s()
        self._consistent_waits.inc()
        t0 = time.perf_counter()
        ok = await ap.wait_applied(required, timeout_s)
        self._consistent_wait_seconds.observe(time.perf_counter() - t0)
        if ok or watch:
            return
        self._consistent_timeouts.inc()
        raise errors.FrontierTimeoutError(
            f"applied_rv {self.store.resource_version} < required "
            f"{required} after {int(timeout_s * 1000)}ms; "
            f"read the primary")

    # -------------------------------------------------------------- watch

    @staticmethod
    def _send_evicted(stream, message: str) -> None:
        """Buffer a terminal typed 410 on an evicted stream WITHOUT a
        drain — the socket may be exactly the full buffer eviction is
        punishing; close flushes what the client still reads."""
        line = (json.dumps({"type": "ERROR",
                            "object": _status_body(410, "Expired", message)})
                .encode() + b"\n")
        try:
            stream.write_raw_many([line])
        except (AttributeError, ConnectionError, RuntimeError):
            pass  # duck-typed test stream or torn-down transport

    def _watch(self, req: Request, cluster: str, res: str,
               namespace: str | None) -> StreamResponse:
        selector = parse_selector(req.param("labelSelector"))
        since = req.param("resourceVersion")
        try:
            since_rv = int(since) if since else None
        except ValueError as e:
            raise errors.BadRequestError(f"malformed resourceVersion {since!r}") from e
        timeout_s = req.param("timeoutSeconds")
        try:
            timeout = float(timeout_s) if timeout_s else None
        except ValueError as e:
            raise errors.BadRequestError(
                f"malformed timeoutSeconds {timeout_s!r}") from e
        import math

        if timeout is not None and (not math.isfinite(timeout) or timeout < 0):
            # nan/inf would turn the deadline math into a busy-spin
            raise errors.BadRequestError(
                f"timeoutSeconds must be a finite non-negative number, "
                f"got {timeout_s!r}")
        bookmarks = req.param("allowWatchBookmarks") in ("true", "1")
        initial_events = req.param("sendInitialEvents") in ("true", "1")
        if initial_events and self._remote:
            # a storage frontend would have to buffer the backend's
            # whole list to re-serve it — exactly what watch-list
            # exists to avoid; the client falls back to list+watch
            raise errors.BadRequestError(
                "sendInitialEvents is not supported on a storage "
                "frontend; list+watch instead")
        # bookmark cadence (KCP_WATCH_BOOKMARK_S): frequent enough that
        # resuming clients lose little window, cheap enough to be noise
        # (apiserver uses ~1/min; our watch windows are smaller)
        bookmark_every = self._bookmark_every

        async def produce(stream: StreamResponse) -> None:
            init_items = init_rv = None
            try:
                if initial_events:
                    # KEP-3157-style watch-list: open the watch and take
                    # the list snapshot in ONE store-loop step, so no
                    # event can fall between them — the ADDED stream
                    # plus the live tail is exactly list-then-watch,
                    # without the client ever holding a whole list body
                    def _open_watch_list():
                        w = self.store.watch(
                            res, cluster, namespace, selector, None)
                        items, rv = self.store.list(
                            res, cluster, namespace, selector)
                        return w, items, rv
                    watch, init_items, init_rv = await self._st(
                        _open_watch_list)
                else:
                    watch = await self._st(
                        self.store.watch, res, cluster, namespace,
                        selector, since_rv)
            except errors.ConflictError as e:
                # expired watch window → 410 Gone in-stream, like the
                # apiserver's "too old resource version"
                await stream.send_json({"type": "ERROR",
                                        "object": _status_body(410, "Expired", e.message)})
                return
            except errors.ApiError as e:
                # a remote-store backend can refuse the watch itself
                # (403 bad --store-token, 404 unknown resource, ...):
                # relay the mapped Status in-stream instead of silently
                # dropping the client connection (ADVICE r5)
                await stream.send_json({
                    "type": "ERROR",
                    "object": _status_body(e.code, e.reason, e.message)})
                return
            if init_items is not None:
                # stream the snapshot as ADDED events in bounded
                # batches, then the sync BOOKMARK that marks the end of
                # initial events — the client is consistent at init_rv
                # and keeps this very stream for the live tail
                send_raw = (getattr(stream, "send_raw_many", None)
                            if self._encode else None)
                if send_raw is not None:
                    batch: list[bytes] = []
                    for obj in init_items:
                        batch.append(b'{"type": "ADDED", "object": '
                                     + self.store.encode_obj(obj) + b"}\n")
                        if len(batch) >= 512:
                            await send_raw(batch)
                            batch = []
                    if batch:
                        await send_raw(batch)
                else:
                    for obj in init_items:
                        await stream.send_json(
                            {"type": "ADDED", "object": obj})
                await stream.send_json({
                    "type": "BOOKMARK",
                    "object": {"kind": "Bookmark", "metadata": {
                        "resourceVersion": str(init_rv),
                        "annotations": {INITIAL_EVENTS_END: "true"}}},
                })
                REGISTRY.counter(
                    "watch_list_streams_total",
                    "watch streams opened with sendInitialEvents").inc()
            loop = asyncio.get_event_loop()
            deadline = loop.time() + timeout if timeout else None
            drain_task: asyncio.Task | None = None
            fence_task: asyncio.Task | None = None

            async def send_batch(batch) -> None:
                # coalesce whatever else the watch already buffered
                # (the store's batched fan-out delivers in bursts)
                # into one chunk/one drain instead of a write per
                # event; drain() never raises, so error mapping below
                # is unaffected. Streams without the batch method
                # (test fakes/duck types) get the per-event sends.
                send_raw = (getattr(stream, "send_raw_many", None)
                            if self._encode else None)
                send_many = getattr(stream, "send_json_many", None)
                if send_raw is not None:
                    # encode-once: every relay serving this store
                    # splices the same cached event-line bytes — a
                    # 64-watcher fan-out encodes each event once
                    t0 = loop.time()
                    lines = self.store.encode_events(batch)
                    self._enc_seconds.observe(loop.time() - t0)
                    if (self._coalescer is not None
                            and getattr(stream, "write_raw_many", None)
                            is not None):
                        # batched flush: lines park with every other
                        # stream's and each socket is written once per
                        # coalescing tick; False = this socket is past
                        # the buffer bound — evict, don't buffer more.
                        # Duck-typed streams without the buffered write
                        # half (test sinks) keep the direct path.
                        if not await self._coalescer.write(stream, lines):
                            raise _SlowWatcher()
                    else:
                        await send_raw(lines)
                elif send_many is not None:
                    await send_many(
                        [{"type": e.type, "object": e.object} for e in batch])
                else:
                    for e in batch:
                        await stream.send_json({"type": e.type,
                                                "object": e.object})

            async def flush_and_terminate() -> None:
                # graceful drain: every event the fan-out already queued
                # is delivered, then a final BOOKMARK anchors the client
                # at the store's true position — DELETED events carry
                # the object's last-written RV, so a client that saw
                # every event can still trail the store RV, and resuming
                # from that trailing RV against the restarted server's
                # empty history would answer a false 410. The terminal
                # in-stream Status then tells the client this stream
                # ends deliberately: resume from the bookmark, nothing
                # was swallowed.
                batch = watch.drain()
                if batch:
                    await send_batch(batch)
                rv_now = (getattr(watch, "last_rv", 0) if self._remote
                          else self.store.resource_version)
                if rv_now:
                    await stream.send_json({
                        "type": "BOOKMARK",
                        "object": {"kind": "Bookmark", "metadata": {
                            "resourceVersion": str(rv_now)}},
                    })
                await stream.send_json({
                    "type": "ERROR",
                    "object": _status_body(
                        503, "ServiceUnavailable",
                        "server is draining; resume from your last "
                        "resourceVersion")})

            nxt: asyncio.Task | None = None
            try:
                it = watch.__aiter__()
                while True:
                    if self.draining.is_set() or self.watch_fence.is_set():
                        await flush_and_terminate()
                        return
                    step = bookmark_every if bookmarks else 3600.0
                    if deadline is not None:
                        step = min(step, max(0.0, deadline - loop.time()))
                    nxt = asyncio.ensure_future(it.__anext__())
                    if drain_task is None:
                        drain_task = asyncio.ensure_future(
                            self.draining.wait())
                    if fence_task is None:
                        fence_task = asyncio.ensure_future(
                            self.watch_fence.wait())
                    done, _ = await asyncio.wait(
                        {nxt, drain_task, fence_task}, timeout=step,
                        return_when=asyncio.FIRST_COMPLETED)
                    ev = None
                    err: BaseException | None = None
                    if nxt in done:
                        try:
                            ev = nxt.result()
                        except BaseException as e:  # noqa: BLE001 — mapped below
                            err = e
                    else:
                        # timeout or drain woke us: reap the in-flight
                        # __anext__ without losing an event that raced in
                        # between wait() returning and the cancel
                        nxt.cancel()
                        try:
                            ev = await nxt
                        except (asyncio.CancelledError, StopAsyncIteration):
                            ev = None
                        except BaseException as e:  # noqa: BLE001 — mapped below
                            err = e
                    if err is not None:
                        if isinstance(err, errors.ConflictError):
                            # remote-store frontends surface an expired
                            # watch window from the first iteration (the
                            # backend's 410 arrives in-stream) rather than
                            # from watch() — translate it the same way so
                            # clients relist instead of seeing a silent
                            # connection drop
                            await stream.send_json({
                                "type": "ERROR",
                                "object": _status_body(410, "Expired",
                                                       err.message)})
                            return
                        if isinstance(err, errors.ApiError):
                            # any other backend refusal mid-relay (403/404/
                            # 5xx mapped by the REST client) ends the stream
                            # with a terminal Status carrying the real code,
                            # not a silent connection drop (ADVICE r5)
                            await stream.send_json({
                                "type": "ERROR",
                                "object": _status_body(err.code, err.reason,
                                                       err.message)})
                            return
                        if isinstance(err, StopAsyncIteration):
                            if getattr(watch, "evicted", False):
                                # backpressure eviction (KCP_WATCH_QUEUE
                                # overflow or the watch.evict drill): a
                                # typed in-stream 410 — the informer
                                # relists NOW and resumes; the metric
                                # was counted at the eviction site
                                self._send_evicted(
                                    stream,
                                    "watch queue overflowed "
                                    "(KCP_WATCH_QUEUE): slow watcher "
                                    "evicted; re-list and resume")
                            return
                        raise err
                    if ev is not None:
                        await send_batch([ev, *watch.drain()])
                        continue
                    if self.draining.is_set() or self.watch_fence.is_set():
                        await flush_and_terminate()
                        return
                    if deadline is not None and loop.time() >= deadline:
                        return  # server-side watch timeout: clean close
                    # only bookmark when nothing is buffered: the store
                    # RV may already cover an event still queued in this
                    # watch, and a client resuming from such a bookmark
                    # would skip that event forever
                    if bookmarks and not watch.pending():
                        # progress marker carrying the current RV so
                        # clients can resume without replay. On a
                        # remote-store frontend the store RV is ahead
                        # of the relayed stream (an event can commit
                        # backend-side while its chunk is still in
                        # flight), so bookmark only what this stream
                        # has DELIVERED (last_rv) — a fresher store
                        # RV would let a resuming client skip that
                        # in-flight event forever.
                        if self._remote:
                            rv_now = getattr(watch, "last_rv", 0)
                            if not rv_now:
                                continue  # nothing delivered yet
                        else:
                            rv_now = self.store.resource_version
                        await stream.send_json({
                            "type": "BOOKMARK",
                            "object": {"kind": "Bookmark", "metadata": {
                                "resourceVersion": str(rv_now)}},
                        })
            except _SlowWatcher:
                # the socket sat past KCP_WATCH_BUFFER_MAX: terminal
                # typed 410 buffered without a drain (draining a full
                # slow socket is exactly what eviction exists to avoid)
                REGISTRY.counter("watch_evicted_total").inc()
                self._send_evicted(
                    stream,
                    "watch socket backlog exceeded KCP_WATCH_BUFFER_MAX: "
                    "slow watcher evicted; re-list and resume")
                return
            finally:
                # reap outstanding helper tasks without awaiting (this
                # block also runs under cancellation): the callback
                # retrieves any late exception (watch.close() below
                # completes a pending __anext__ with StopAsyncIteration)
                # so the loop never logs "exception was never retrieved"
                for t in (nxt, drain_task, fence_task):
                    if t is not None and not t.done():
                        t.cancel()
                    if t is not None:
                        t.add_done_callback(
                            lambda t: t.cancelled() or t.exception())
                watch.close()

        return StreamResponse(produce)


def render_kubeconfig(address: str, path: str, token: str = "",
                      ca_pem: bytes | None = None) -> None:
    """Write an admin kubeconfig-style file with admin + user contexts.

    Mirrors the reference writing .kcp/admin.kubeconfig with contexts
    ``admin`` and ``user`` (the latter scoped to /clusters/user)
    (reference: pkg/server/server.go:151-176). When RBAC-lite is on,
    the minted admin bearer token rides along as the user credential;
    with TLS, the CA certificate rides as certificate-authority-data so
    clients verify the self-signed endpoint."""
    users = [{"name": "admin", "user": ({"token": token} if token else {})}]
    cluster_fields = {}
    if ca_pem is not None:
        import base64

        cluster_fields["certificate-authority-data"] = base64.b64encode(
            ca_pem).decode("ascii")
    cfg = {
        "kind": "Config", "apiVersion": "v1",
        "clusters": [
            {"name": "admin", "cluster": {"server": address, **cluster_fields}},
            {"name": "user", "cluster": {"server": f"{address}/clusters/user",
                                         **cluster_fields}},
        ],
        "users": users,
        "contexts": [
            {"name": "admin", "context": {"cluster": "admin", "user": "admin"}},
            {"name": "user", "context": {"cluster": "user", "user": "admin"}},
        ],
        "current-context": "admin",
    }
    # 0600: the file may carry a cluster-admin bearer token (kubeconfig
    # convention)
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        json.dump(cfg, f, indent=2)
