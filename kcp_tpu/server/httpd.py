"""Minimal asyncio HTTP/1.1 server for the control-plane REST surface.

Only what the API surface needs: request-line + header parsing,
Content-Length bodies, one-shot JSON responses, and chunked streaming
responses for watches. TLS via an ``ssl.SSLContext`` (the server's
self-signed serving certs, kcp_tpu/server/certs.py — parity with the
reference's generated-cert TLS endpoint, pkg/etcd/etcd.go:98-188 +
pkg/server/server.go:151-176).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from dataclasses import dataclass
from typing import Awaitable, Callable
from urllib.parse import parse_qs, unquote, urlsplit

from ..utils.trace import REGISTRY, SIZE_BUCKETS

log = logging.getLogger(__name__)

#: stream flush operations — one buffered chunk write (plus, on the
#: non-coalesced paths, its drain round trip) per socket. The watcher-
#: scale A/B (`bench.py --watchers`) reads the per-fan-out reduction off
#: this counter.
_FLUSHES = REGISTRY.counter(
    "watch_flush_total",
    "watch-stream flush operations (one chunk write per socket)")
_FLUSH_BATCH = REGISTRY.histogram(
    "watch_flush_batch_size",
    "event lines merged into one stream flush", buckets=SIZE_BUCKETS)
#: the zero-copy wire meters: spans handed to the transport through the
#: scatter path (no whole-body b"".join), and the bytes that skipped the
#: full-body join copy because of it. `bench.py --smartclient` proves the
#: scatter path byte-identical to the join path (sha256 over the wire).
_SPANS_WRITTEN = REGISTRY.counter(
    "wire_spans_written_total",
    "encode-once byte spans written through the scatter wire path "
    "(KCP_WIRE_SCATTER) without an intermediate whole-body join")
_JOIN_AVOIDED = REGISTRY.counter(
    "wire_join_avoided_total",
    "response-body bytes written without the whole-body b''.join copy "
    "the legacy wire path paid (scatter path only)")

MAX_HEADER_BYTES = 64 * 1024
# listener accept backlog: a 10k-watcher reconnect storm lands thousands
# of TCP connects in the same instant — the asyncio default (100) would
# refuse most of the herd and stretch resume latency by retry round
# trips (kernel still caps at net.core.somaxconn)
LISTEN_BACKLOG = int(os.environ.get("KCP_LISTEN_BACKLOG", "4096"))
# request-body ceiling (KCP_MAX_BODY_BYTES): the cheapest admission
# control of all — a declared body over the limit is refused 413 before
# a single payload byte is buffered. 3 MiB default ~= the apiserver's
# etcd request ceiling; read at import, overridable per-process.
MAX_BODY_BYTES = int(os.environ.get("KCP_MAX_BODY_BYTES", str(3 * 1024 * 1024)))
# spans below this size coalesce into one bounded join before hitting
# the transport (a send syscall per 200-byte watch line would cost more
# than the copy it saves); spans at or above it go to the transport
# as-is — the writev-spirit scatter path for big encode-once spans
# (pre-joined bucket spans, large objects)
SCATTER_MIN = int(os.environ.get("KCP_WIRE_SCATTER_MIN", str(16 * 1024)))


def scatter_enabled() -> bool:
    """KCP_WIRE_SCATTER (default on): scatter/writev-style body writes —
    span lists are handed to the transport without the whole-body
    ``b"".join`` (big spans go as-is; small ones coalesce into bounded
    <= SCATTER_MIN join buffers). ``=0`` restores the single-join wire
    path for A/B; both produce byte-identical streams. Read per response
    (one dict probe) so tests and benches can flip it on a live server."""
    return os.environ.get("KCP_WIRE_SCATTER", "1").lower() not in (
        "0", "false", "off")


def _write_parts(writer: asyncio.StreamWriter, parts) -> None:
    """Write ``parts`` (framing + spans) to the transport without one
    whole-body join: spans >= SCATTER_MIN are written as-is (the bytes
    the encode cache holds are the bytes on the wire — no intermediate
    copy), smaller ones coalesce into bounded join buffers so tiny
    spans don't become per-span syscalls."""
    small: list[bytes] = []
    small_len = 0
    spans = 0
    avoided = 0
    for p in parts:
        if len(p) >= SCATTER_MIN:
            if small:
                writer.write(small[0] if len(small) == 1 else b"".join(small))
                small = []
                small_len = 0
            writer.write(p)
            spans += 1
            avoided += len(p)
        else:
            small.append(p)
            small_len += len(p)
            if small_len >= SCATTER_MIN:
                writer.write(small[0] if len(small) == 1
                             else b"".join(small))
                spans += 1
                small = []
                small_len = 0
    if small:
        writer.write(small[0] if len(small) == 1 else b"".join(small))
        spans += 1
    _SPANS_WRITTEN.inc(spans)
    if avoided:
        _JOIN_AVOIDED.inc(avoided)


class RequestTooLarge(Exception):
    """Raised by request parsing when Content-Length exceeds
    MAX_BODY_BYTES; the connection loop answers 413 and closes (the
    unread body makes the connection unusable for keep-alive)."""

    def __init__(self, size: int):
        super().__init__(f"request body {size} bytes exceeds limit")
        self.size = size


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, list[str]]
    headers: dict[str, str]  # keys lower-cased
    body: bytes
    # the request target exactly as it appeared on the request line
    # (still percent-encoded, query included) — what a proxy (the shard
    # router) forwards so relayed requests stay byte-identical
    target: str = ""

    def param(self, name: str, default: str | None = None) -> str | None:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def json(self):
        if not self.body:
            return None
        return json.loads(self.body)


class Response:
    """One-shot response. ``spans`` is the zero-copy body form: a list of
    byte spans whose concatenation IS the body (the handler's encode-once
    list assembly hands the cached spans straight through and the wire
    path writes them scatter-style, never paying the whole-body join).
    ``.body`` stays correct for direct consumers — it joins lazily on
    first access and memoizes; the HTTP write path checks ``spans``
    first and never triggers that join while scatter is on."""

    def __init__(self, status: int = 200, body: bytes = b"",
                 content_type: str = "application/json",
                 headers: dict[str, str] | None = None,
                 spans: list[bytes] | None = None):
        self.status = status
        self._body = body
        self.content_type = content_type
        self.headers: dict[str, str] = headers if headers is not None else {}
        self.spans = spans

    @property
    def body(self) -> bytes:
        if self.spans is not None and not self._body:
            self._body = b"".join(self.spans)
        return self._body

    @body.setter
    def body(self, value: bytes) -> None:
        self._body = value
        self.spans = None

    def body_len(self) -> int:
        """Content-Length without materializing a joined body."""
        if self.spans is not None and not self._body:
            return sum(len(s) for s in self.spans)
        return len(self._body)

    @classmethod
    def of_json(cls, obj, status: int = 200) -> "Response":
        return cls(status=status, body=json.dumps(obj).encode())


class StreamResponse:
    """A chunked-transfer streaming response (the watch wire format).

    The handler returns one of these; the connection loop then calls
    :meth:`send_json` per event until the producer finishes or the client
    disconnects.
    """

    def __init__(self, producer: Callable[["StreamResponse"], Awaitable[None]],
                 status: int = 200):
        self.status = status
        self.producer = producer
        self._writer: asyncio.StreamWriter | None = None

    async def _begin(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer
        writer.write(
            f"HTTP/1.1 {self.status} {_reason(self.status)}\r\n"
            "Content-Type: application/json\r\n"
            "Transfer-Encoding: chunked\r\n"
            "Connection: close\r\n\r\n".encode()
        )
        await writer.drain()

    async def send_json(self, obj) -> None:
        assert self._writer is not None
        data = json.dumps(obj).encode() + b"\n"
        self._writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        await self._writer.drain()

    async def send_json_many(self, objs) -> None:
        """All objects as ndjson lines in ONE chunk + one drain — the
        watch relay's wire-level fan-out batching. Clients reassemble by
        newline (RestWatch already splits chunk payloads on ``\\n``), so
        framing is unchanged; a burst of N events costs one syscall
        instead of N."""
        assert self._writer is not None
        if not objs:
            return
        data = b"".join(json.dumps(o).encode() + b"\n" for o in objs)
        self._writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        _FLUSHES.inc()
        _FLUSH_BATCH.observe(len(objs))
        await self._writer.drain()

    async def send_raw_many(self, lines) -> None:
        """Pre-encoded newline-terminated JSON lines in ONE chunk + one
        drain — the encode-once twin of :meth:`send_json_many`. The relay
        hands every watcher the same cached bytes (store.encode_event),
        so a 64-way fan-out costs one encode instead of 64; the chunked
        framing is byte-identical to the json path."""
        assert self._writer is not None
        if not lines:
            return
        self.write_raw_many(lines)
        await self._writer.drain()

    async def send_spans(self, lines) -> None:
        """The raw-spans twin of :meth:`send_json_many`: encode-once byte
        spans framed as ONE chunk and written scatter-style (no
        whole-chunk ``b"".join`` while ``KCP_WIRE_SCATTER`` is on) + one
        drain. The replication hub's batch sends ride this — a catchup
        tail of N pre-encoded WAL records costs zero re-encodes and zero
        whole-batch join copies."""
        await self.send_raw_many(lines)

    def write_raw_many(self, lines) -> None:
        """Frame pre-encoded lines as ONE chunk and buffer them on the
        transport WITHOUT draining — the :class:`FlushCoalescer`'s write
        half. Backpressure is handled by eviction (the coalescer checks
        the transport buffer against ``KCP_WATCH_BUFFER_MAX``), never by
        awaiting a slow socket. With ``KCP_WIRE_SCATTER`` on, the lines
        go to the transport as spans (bounded coalescing, no whole-chunk
        join); ``=0`` keeps the legacy single-join write — byte-identical
        either way (same bytes, same single chunk frame)."""
        assert self._writer is not None
        if not lines:
            return
        tr = self._writer.transport
        if tr is None or tr.is_closing():
            raise ConnectionResetError("stream transport closed")
        total = sum(len(ln) for ln in lines)
        if not total:
            return  # an all-empty batch must not emit a terminal 0-chunk
        if scatter_enabled():
            _write_parts(self._writer,
                         [f"{total:x}\r\n".encode(), *lines, b"\r\n"])
        else:
            data = b"".join(lines)
            self._writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        _FLUSHES.inc()
        _FLUSH_BATCH.observe(len(lines))

    async def relay_chunk(self, size_line: bytes, payload: bytes) -> None:
        """Forward one upstream chunk frame verbatim (the router's
        zero-parse relay): the upstream's own length-delimited framing
        and payload bytes go to the transport untouched — no decode, no
        line split, no re-frame, no join."""
        assert self._writer is not None
        tr = self._writer.transport
        if tr is None or tr.is_closing():
            raise ConnectionResetError("stream transport closed")
        self._writer.write(size_line)
        self._writer.write(payload)
        _FLUSHES.inc()
        await self._writer.drain()

    def write_buffer_size(self) -> int:
        """Bytes buffered on this stream's transport — the slow-client
        signal the coalescer's eviction policy reads."""
        w = self._writer
        if w is None or w.transport is None:
            return 0
        try:
            return w.transport.get_write_buffer_size()
        except Exception:  # noqa: BLE001 — transport torn down mid-call
            return 0

    async def _finish(self) -> None:
        if self._writer is not None:
            try:
                self._writer.write(b"0\r\n\r\n")
                await self._writer.drain()
            except (ConnectionError, RuntimeError):
                pass


class FlushCoalescer:
    """Batches watch-stream writes across many sockets into one
    event-loop pass (``KCP_WATCH_COALESCE``).

    Producers call ``await write(stream, lines)``; lines park per-stream
    and the whole map flushes after one coalescing tick
    (``KCP_WATCH_FLUSH_MS``): each socket gets ONE joined chunk write
    per tick no matter how many event batches accumulated, so a
    sustained fan-out to N watchers costs O(sockets) buffered writes of
    shared encode-once bytes per tick instead of O(batches × watchers)
    write+drain round trips.

    Backpressure is by eviction, not drain: the flush never awaits a
    slow socket. A stream whose transport buffer exceeds ``buffer_max``
    (``KCP_WATCH_BUFFER_MAX``) resolves its producer's future ``False``
    — the producer ends the stream with a terminal typed 410 and the
    informer's relist-NOW path takes over. Everyone else's tick is never
    held hostage by the slowest reader.
    """

    def __init__(self, tick_s: float = 0.002,
                 buffer_max: int = 2 * 1024 * 1024):
        self.tick_s = tick_s
        self.buffer_max = buffer_max
        self._pending: dict[StreamResponse,
                            tuple[list[bytes], asyncio.Future]] = {}
        self._scheduled = False

    def write(self, stream: StreamResponse, lines) -> "asyncio.Future[bool]":
        """Park ``lines`` for ``stream``; the returned future resolves
        True once flushed (False = over the buffer bound: evict)."""
        loop = asyncio.get_running_loop()
        ent = self._pending.get(stream)
        if ent is None:
            ent = self._pending[stream] = ([], loop.create_future())
        ent[0].extend(lines)
        if not self._scheduled:
            self._scheduled = True
            if self.tick_s > 0:
                loop.call_later(self.tick_s, self._flush)
            else:
                loop.call_soon(self._flush)
        return ent[1]

    def _flush(self) -> None:
        self._scheduled = False
        pending, self._pending = self._pending, {}
        for stream, (lines, fut) in pending.items():
            if fut.done():
                continue  # producer cancelled (client went away)
            try:
                stream.write_raw_many(lines)
            except Exception as e:  # noqa: BLE001 — surfaced to the producer
                fut.set_exception(e)
                continue
            fut.set_result(stream.write_buffer_size() <= self.buffer_max)


_REASONS = {200: "OK", 201: "Created", 400: "Bad Request", 403: "Forbidden",
            404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
            410: "Gone", 413: "Request Entity Too Large",
            422: "Unprocessable Entity", 429: "Too Many Requests",
            500: "Internal Server Error", 503: "Service Unavailable"}


def _reason(status: int) -> str:
    return _REASONS.get(status, "Unknown")


Handler = Callable[[Request], Awaitable["Response | StreamResponse"]]


class HttpServer:
    """asyncio.start_server wrapper dispatching to a single handler."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self.handler = handler
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.Task] = set()
        # graceful drain state: once draining, the listener is closed
        # (late connections are refused at the TCP level), idle
        # keep-alive connections are torn down, and in-flight responses
        # force ``Connection: close``
        self._draining = False
        self._idle: set[asyncio.StreamWriter] = set()
        self._busy = 0  # requests currently between parse and response

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port, ssl=self.ssl_context,
            backlog=LISTEN_BACKLOG)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("http%s server listening on %s:%d",
                 "s" if self.ssl_context else "", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # long-lived watch streams never finish on their own — cancel
            # them or wait_closed() blocks forever
            for task in list(self._conns):
                task.cancel()
            await asyncio.gather(*self._conns, return_exceptions=True)
            await self._server.wait_closed()
            self._server = None

    @property
    def address(self) -> str:
        scheme = "https" if self.ssl_context else "http"
        return f"{scheme}://{self.host}:{self.port}"

    # ------------------------------------------------------------- drain

    def begin_drain(self) -> None:
        """Stop accepting work: close the listener (late connections are
        refused), tear down idle keep-alive connections, and mark every
        in-flight response ``Connection: close``. In-flight requests and
        open streams keep running — :meth:`finish_drain` bounds them."""
        self._draining = True
        if self._server is not None:
            self._server.close()
        for w in list(self._idle):
            try:
                w.close()
            except Exception:  # noqa: BLE001 — already-dead transport
                pass

    async def wait_requests_idle(self, deadline: float) -> bool:
        """Wait until no request is between parse and response write
        (watch streams excluded — they end via the handler's drain
        signal). Returns False if the deadline expired first."""
        loop = asyncio.get_running_loop()
        while self._busy > 0:
            if loop.time() >= deadline:
                return False
            await asyncio.sleep(0.005)
        return True

    async def finish_drain(self, deadline: float) -> int:
        """Wait for every connection task to finish (stream producers
        end once the handler's draining signal is set); tasks still
        alive at the deadline are cancelled. Returns the forced count."""
        forced = 0
        conns = set(self._conns)
        if conns:
            loop = asyncio.get_running_loop()
            timeout = max(0.0, deadline - loop.time())
            _done, pending = await asyncio.wait(conns, timeout=timeout)
            for t in pending:
                t.cancel()
                forced += 1
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None
        return forced

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conns.add(task)
            task.add_done_callback(self._conns.discard)
        try:
            while True:
                self._idle.add(writer)
                try:
                    req = await self._read_request(reader)
                except RequestTooLarge as e:
                    # 413 instead of buffering: the body was never read,
                    # so answer and close rather than resynchronize
                    body = json.dumps({
                        "kind": "Status", "apiVersion": "v1",
                        "status": "Failure",
                        "reason": "RequestEntityTooLarge",
                        "message": (f"request body of {e.size} bytes exceeds "
                                    f"the {MAX_BODY_BYTES}-byte limit "
                                    f"(KCP_MAX_BODY_BYTES)"),
                        "code": 413,
                    }).encode()
                    writer.write(
                        f"HTTP/1.1 413 {_reason(413)}\r\n"
                        "Content-Type: application/json\r\n"
                        f"Content-Length: {len(body)}\r\n"
                        "Connection: close\r\n\r\n".encode() + body)
                    await writer.drain()
                    break
                finally:
                    self._idle.discard(writer)
                if req is None:
                    break
                keep = True
                self._busy += 1
                try:
                    try:
                        resp = await self.handler(req)
                    except Exception:  # handler bug — surface as 500, keep serving
                        log.exception("handler error for %s %s",
                                      req.method, req.path)
                        resp = Response.of_json(
                            {"kind": "Status", "status": "Failure",
                             "reason": "InternalError", "code": 500}, 500)
                    if not isinstance(resp, StreamResponse):
                        # draining forces Connection: close so keep-alive
                        # clients re-resolve instead of queueing more
                        # requests on a server that is going away
                        keep = (req.headers.get("connection", "keep-alive")
                                != "close") and not self._draining
                        head = (
                            f"HTTP/1.1 {resp.status} {_reason(resp.status)}\r\n"
                            f"Content-Type: {resp.content_type}\r\n"
                            f"Content-Length: {resp.body_len()}\r\n"
                        )
                        for k, v in resp.headers.items():
                            head += f"{k}: {v}\r\n"
                        head += ("Connection: "
                                 f"{'keep-alive' if keep else 'close'}\r\n\r\n")
                        if resp.spans is not None and scatter_enabled():
                            # zero-copy body: the encode-once spans go to
                            # the transport without the whole-body join
                            _write_parts(writer,
                                         [head.encode(), *resp.spans])
                        else:
                            writer.write(head.encode() + resp.body)
                        await writer.drain()
                finally:
                    self._busy -= 1
                if isinstance(resp, StreamResponse):
                    await resp._begin(writer)
                    # watch the socket for client disconnect: an idle stream
                    # never writes, so EOF would otherwise go unnoticed and
                    # the producer (and its store subscription) would leak
                    monitor = asyncio.ensure_future(reader.read(1))
                    producer = asyncio.ensure_future(resp.producer(resp))
                    try:
                        await asyncio.wait({monitor, producer},
                                           return_when=asyncio.FIRST_COMPLETED)
                    finally:
                        for t in (monitor, producer):
                            t.cancel()
                        results = await asyncio.gather(
                            monitor, producer, return_exceptions=True)
                        for r in results:
                            if isinstance(r, Exception) and not isinstance(
                                r, (asyncio.CancelledError, ConnectionError)
                            ):
                                log.error(
                                    "stream producer failed", exc_info=r)
                    await resp._finish()
                    break  # streams always close the connection
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            pass
        except asyncio.CancelledError:
            # server stop cancelled this connection task: a graceful TLS
            # close would block on the peer's close_notify until the SSL
            # shutdown timeout (observed: 30s per idle keep-alive conn) —
            # abort the transport so stop() returns promptly
            transport = writer.transport
            if transport is not None:
                transport.abort()
            raise
        finally:
            try:
                # graceful close: unbounded, so large in-flight responses
                # to slow readers always flush fully
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, RuntimeError, TimeoutError,
                    asyncio.CancelledError):
                pass

    async def _read_request(self, reader: asyncio.StreamReader) -> Request | None:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        if len(head) > MAX_HEADER_BYTES:
            return None
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, target, _version = lines[0].split(" ", 2)
        except ValueError:
            return None
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        body = b""
        clen = int(headers.get("content-length", "0") or "0")
        if clen:
            if clen > MAX_BODY_BYTES:
                raise RequestTooLarge(clen)
            body = await reader.readexactly(clen)
        parts = urlsplit(target)
        return Request(
            method=method.upper(),
            path=unquote(parts.path),
            query=parse_qs(parts.query),
            headers=headers,
            body=body,
            target=target,
        )
