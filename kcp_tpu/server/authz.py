"""RBAC-lite: bearer-token authentication + per-tenant RBAC evaluation.

The reference gets authn/authz from its forked generic control plane —
the minimal apiserver explicitly keeps RBAC among the built-in resources
(docs/investigations/minimal-api-server.md; the fork wires the standard
RBAC authorizer) and the scheme here already serves `clusterroles` /
`clusterrolebindings` (kcp_tpu/apis/scheme.py). This module makes those
objects mean something:

- **Authentication**: ``Authorization: Bearer <token>`` resolved against
  a static token table (the reference's admin.kubeconfig model: tokens
  minted at startup, server.go:151-176). No token -> the anonymous user.
- **Authorization**: RBAC evaluated *per logical cluster* — bindings in
  tenant A grant nothing in tenant B (tenancy is the whole point of the
  logical-cluster model). Wildcard ``*`` verbs/groups/resources are
  supported; the well-known ``cluster-admin`` role name short-circuits.
- Cross-tenant wildcard reads (``/clusters/*``) require the caller to be
  admin in the root cluster, since they traverse every tenant at once.
- **Escalation prevention** (Kubernetes' RBAC escalation check, which
  the reference inherits from its forked generic control plane): writes
  to ``clusterroles`` are denied unless the writer already holds every
  permission the role grants (or holds the ``escalate`` verb on
  clusterroles); writes to ``clusterrolebindings`` are denied unless the
  writer already holds the referenced role's permissions (or holds the
  ``bind`` verb on clusterroles). Without this, any user granted
  ``create`` on clusterrolebindings could bind themselves cluster-admin.

Evaluation is pure host-side policy (small, irregular, latency-bound —
nothing to batch); enforcement sits in the REST handler so the
in-process Client, like the reference's loopback client, stays
privileged. Default OFF (Config.authz) to keep the open-prototype
behavior the reference ships with.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..store.store import WILDCARD, LogicalStore
from ..utils.errors import NotFoundError

ANONYMOUS = "system:anonymous"
ADMIN_USER = "admin"
CLUSTER_ADMIN_ROLE = "cluster-admin"
ROOT_CLUSTER = "admin"  # the default logical cluster of admin.kubeconfig

CLUSTERROLES = "clusterroles.rbac.authorization.k8s.io"
BINDINGS = "clusterrolebindings.rbac.authorization.k8s.io"


@dataclass
class Authenticator:
    """Static bearer-token table (token -> user name)."""

    tokens: dict[str, str] = field(default_factory=dict)

    def user_for(self, headers: dict[str, str]) -> str:
        auth = headers.get("authorization", "")
        if auth.lower().startswith("bearer "):
            token = auth[7:].strip()
            user = self.tokens.get(token)
            if user:
                return user
        return ANONYMOUS


def _rule_matches(rule: dict, verb: str, group: str, resource: str) -> bool:
    def hit(allowed, value):
        return "*" in allowed or value in allowed

    return (
        hit(rule.get("verbs", []), verb)
        and hit(rule.get("apiGroups", [""]), group)
        and hit(rule.get("resources", []), resource)
    )


class Authorizer:
    """Per-logical-cluster RBAC evaluation over the live store."""

    def __init__(self, store: LogicalStore):
        self.store = store

    def _roles_for(self, user: str, cluster: str) -> list[str]:
        bindings, _ = self.store.list(BINDINGS, cluster)
        out = []
        for b in bindings:
            for subj in b.get("subjects", []):
                if subj.get("kind", "User") == "User" and subj.get("name") == user:
                    out.append(b.get("roleRef", {}).get("name", ""))
        return out

    def allowed(self, user: str, cluster: str, verb: str, group: str,
                resource: str) -> bool:
        if user == ADMIN_USER:
            return True  # the minted admin identity is cluster-admin everywhere
        if cluster == WILDCARD:
            # cross-tenant traversal: only root-cluster admins (implies
            # any per-rule grant, so one membership test suffices)
            return CLUSTER_ADMIN_ROLE in self._roles_for(user, ROOT_CLUSTER)
        for role_name in self._roles_for(user, cluster):
            if role_name == CLUSTER_ADMIN_ROLE:
                return True
            try:
                role = self.store.get(CLUSTERROLES, cluster, role_name)
            except NotFoundError:
                continue  # dangling roleRef grants nothing
            for rule in role.get("rules", []):
                if _rule_matches(rule, verb, group, resource):
                    return True
        return False

    # ------------------------------------------------- escalation check

    def _covers(self, user: str, cluster: str, rules: list) -> bool:
        """Does the user already hold every permission ``rules`` grants?
        Wildcards are only covered by wildcards (a user without ``*``
        cannot grant ``*``), matching Kubernetes' covers semantics.

        The user's effective rule set is resolved ONCE (one binding list
        + one get per bound role), then each requested permission is
        cover-matched in memory — a wide submitted role must not amplify
        into per-combination store evaluations. Rules that are not even
        dict-shaped cannot be verified and are denied."""
        if user == ADMIN_USER:
            return True
        held: list[dict] = []
        for role_name in self._roles_for(user, cluster):
            if role_name == CLUSTER_ADMIN_ROLE:
                return True
            try:
                role = self.store.get(CLUSTERROLES, cluster, role_name)
            except NotFoundError:
                continue
            held.extend(r for r in role.get("rules", []) if isinstance(r, dict))
        for rule in rules:
            if not isinstance(rule, dict):
                return False
            for verb in rule.get("verbs", []):
                for group in rule.get("apiGroups", [""]):
                    for resource in rule.get("resources", []):
                        if not any(_rule_matches(h, verb, group, resource)
                                   for h in held):
                            return False
        return True

    def escalation_denied(self, user: str, cluster: str, resource: str,
                          body: dict | None) -> str | None:
        """For a clusterrole/clusterrolebinding write, a denial message if
        the writer would grant permissions they do not hold; None = allow.

        Mirrors Kubernetes' RBAC escalation prevention: the ``escalate``
        verb (on clusterroles) bypasses the role check, the ``bind`` verb
        bypasses the binding check."""
        if user == ADMIN_USER:
            return None
        body = body or {}
        if resource == "clusterroles":
            if self.allowed(user, cluster, "escalate",
                            "rbac.authorization.k8s.io", "clusterroles"):
                return None
            if not self._covers(user, cluster, body.get("rules", [])):
                return (f'user "{user}" cannot create/update a clusterrole '
                        f"granting permissions they do not hold "
                        f"(escalation check; needs the \"escalate\" verb)")
        elif resource == "clusterrolebindings":
            if self.allowed(user, cluster, "bind",
                            "rbac.authorization.k8s.io", "clusterroles"):
                return None
            role_name = (body.get("roleRef") or {}).get("name", "")
            if role_name == CLUSTER_ADMIN_ROLE:
                if CLUSTER_ADMIN_ROLE in self._roles_for(user, cluster):
                    return None
                return (f'user "{user}" cannot bind "{CLUSTER_ADMIN_ROLE}" '
                        f"without holding it (escalation check)")
            try:
                role = self.store.get(CLUSTERROLES, cluster, role_name)
            except NotFoundError:
                # binding a nonexistent role grants nothing today, but a
                # later role create would retroactively arm it — deny
                return (f'user "{user}" cannot bind nonexistent role '
                        f'"{role_name}" (escalation check)')
            if not self._covers(user, cluster, role.get("rules", [])):
                return (f'user "{user}" cannot bind role "{role_name}" '
                        f"granting permissions they do not hold "
                        f"(escalation check; needs the \"bind\" verb)")
        return None


def verb_for(method: str, has_name: bool, is_watch: bool) -> str:
    if is_watch:
        return "watch"
    if method == "GET":
        return "get" if has_name else "list"
    return {"POST": "create", "PUT": "update", "PATCH": "patch",
            "DELETE": "delete"}.get(method, method.lower())
