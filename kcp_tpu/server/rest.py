"""RestClient: the Client interface spoken over HTTP.

The out-of-process analog of the reference's client-go REST clients: the
standalone binaries (cluster-controller, syncer, deployment-splitter,
crd-puller — reference cmd/*/main.go) connect to a kcp server with a
kubeconfig; here they construct a RestClient against the server address.
Implements the same interface as :class:`kcp_tpu.client.Client`, so every
controller runs equally in-process (store-backed) or remote (HTTP).

Watch streams are chunked-transfer JSON lines (see server.handler._watch);
RestWatch reassembles them into store Events so the shared Informer works
unchanged over the wire.
"""

from __future__ import annotations

import asyncio
import codecs
import http.client
import json
import os
import threading
import time
from urllib.parse import quote, urlsplit

from .. import obs
from ..analysis.sanitize import make_lock
from ..apis.scheme import GVR, ResourceInfo, Scheme, default_scheme
from ..faults import link_fault, maybe_fail, should_drop
from ..store.selectors import LabelSelector
from ..store.store import INITIAL_EVENTS_END, WILDCARD, Event
from ..utils import errors
from ..utils.circuit import CircuitBreaker
from ..utils.routing import resolve_write_cluster


def _status_error(code: int, reason: str, message: str,
                  details: dict | None = None,
                  retry_after: float | None = None) -> errors.ApiError:
    """Map a Status (code, reason) to the ApiError taxonomy — shared by
    response handling and in-stream watch ERROR events. 429s become the
    typed TooManyRequestsError carrying the server's Retry-After pacing
    hint (header or Status ``details.retryAfterSeconds``)."""
    by_reason = {
        "NotFound": errors.NotFoundError,
        "AlreadyExists": errors.AlreadyExistsError,
        "Conflict": errors.ConflictError,
        "Invalid": errors.InvalidError,
        "BadRequest": errors.BadRequestError,
        "Forbidden": errors.ForbiddenError,
        "TooManyRequests": errors.TooManyRequestsError,
        "ServiceUnavailable": errors.UnavailableError,
        "FrontierWaitTimeout": errors.FrontierTimeoutError,
        "Expired": errors.GoneError,
        "Gone": errors.GoneError,
    }
    cls = by_reason.get(reason)
    if cls is None:
        cls = {404: errors.NotFoundError, 409: errors.ConflictError,
               410: errors.GoneError,
               422: errors.InvalidError, 400: errors.BadRequestError,
               403: errors.ForbiddenError,
               429: errors.TooManyRequestsError,
               503: errors.UnavailableError,
               504: errors.FrontierTimeoutError}.get(code, errors.ApiError)
    err = cls(message)
    if cls is errors.ApiError and code >= 400:
        # codes without a dedicated class (401/...) keep their real
        # code + reason on the instance so relays don't flatten to 500
        err.code = code
        if reason:
            err.reason = reason
    if isinstance(err, errors.TooManyRequestsError):
        hint = (details or {}).get("retryAfterSeconds", retry_after)
        try:
            err.retry_after = max(0.0, float(hint))
        except (TypeError, ValueError):
            pass  # class default (1.0) stands
    elif isinstance(err, errors.UnavailableError):
        # lag-shed 503s carry a computed Retry-After (replica lag /
        # apply rate): informers back off exactly as long as catch-up
        # needs instead of the generic jittered retry
        hint = (details or {}).get("retryAfterSeconds", retry_after)
        try:
            err.retry_after = max(0.0, float(hint))
        except (TypeError, ValueError):
            pass  # no hint: callers keep their generic backoff
    return err


def _raise_for_status(code: int, body: bytes,
                      retry_after: float | None = None,
                      headers: dict[str, str] | None = None) -> None:
    """Map an HTTP error status to the typed ApiError. ``headers`` (the
    response headers, lower-cased keys) ride the raised error as
    ``err.http_headers`` — relayed errors keep ``Retry-After`` /
    ``X-Kcp-Ring-Epoch`` visible to callers on the direct path too (the
    smart client's ring-staleness detection and PR 4's 429 pacing both
    read them)."""
    if code < 400:
        return
    try:
        status = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        status = {}
    message = status.get("message", body.decode("latin-1")[:200])
    err = _status_error(code, status.get("reason", ""), message,
                        details=status.get("details"),
                        retry_after=retry_after)
    err.http_headers = headers or {}
    raise err


def _list_page_size() -> int:
    """Transparent list-chunking page size (KCP_LIST_PAGE, default
    10000; ``0`` restores the legacy one-shot list — the A/B lane).
    Read per call so tests and scenario phases can flip it live."""
    try:
        return int(os.environ.get("KCP_LIST_PAGE", "10000") or "0")
    except ValueError:
        return 10000


def _session_rv_enabled() -> bool:
    """Session read-your-writes (KCP_SESSION_RV, default on): clients
    track the max RV observed from their own write acks and watch
    streams per cluster and stamp it as ``X-Kcp-Min-Rv`` on subsequent
    reads — any replica then serves them no staler than the session's
    own past (KEP-2340 consistent reads). ``0`` restores the plain
    any-staleness read path."""
    return os.environ.get("KCP_SESSION_RV", "1").lower() not in (
        "0", "false", "off")


def _path_cluster(path: str) -> str:
    """The ``/clusters/<name>/`` tenant a request path targets; ""
    for non-cluster paths and the wildcard — RVs are per-store
    sequences, so a session floor is only meaningful against the one
    cluster (= shard) that minted it."""
    if not path.startswith("/clusters/"):
        return ""
    c = path[len("/clusters/"):].partition("/")[0].partition("?")[0]
    return "" if c in ("", WILDCARD) else c


class _SessionRv:
    """Per-cluster session read-your-writes floor, SHARED across every
    scoped() clone of one client (the holder object rides the
    ``__dict__`` copy, like the smart client's ring state): the max RV
    this session observed from its own write acks and watch streams.
    Thread-safe — scenario writers and watch feed tasks update it
    concurrently."""

    def __init__(self):
        self._lock = make_lock("rest.session")
        self._floor: dict[str, int] = {}

    def note(self, cluster: str, rv) -> None:
        if not cluster:
            return
        try:
            rv = int(rv)
        except (TypeError, ValueError):
            return
        if rv <= 0:
            return
        with self._lock:
            if rv > self._floor.get(cluster, 0):
                self._floor[cluster] = rv

    def floor(self, cluster: str) -> int:
        if not cluster:
            return 0
        with self._lock:
            return self._floor.get(cluster, 0)


class RestWatch:
    """Async iterator over a server watch stream, yielding store Events.

    Duck-types the parts of :class:`kcp_tpu.store.store.Watch` that
    informers and syncers use: ``async for``, :meth:`next_batch`,
    :meth:`drain`, :meth:`close`.
    """

    # class-level default so a skeletal instance (tests build one via
    # ``__new__`` to drive ``_feed`` directly) still parses bookmarks
    _initial_events = False
    # session read-your-writes: when a _SessionRv rides along, every
    # observed event/bookmark RV raises the session floor (class-level
    # defaults keep skeletal __new__ instances working)
    _session = None
    _session_cluster = ""
    # source name for peer-scoped link faults (link.partition/link.delay);
    # the destination is the watched server's host:port
    link_src = "watch"

    def __init__(self, host: str, port: int, path: str, resource: str,
                 token: str = "", ssl_context=None,
                 extra_headers: dict[str, str] | None = None,
                 initial_events: bool = False,
                 session=None, session_cluster: str = ""):
        self._host = host
        self._port = port
        self._path = path
        self._token = token
        self._ssl = ssl_context
        # watch-list mode: the initial-events-end BOOKMARK is yielded
        # (instead of absorbed) so the informer knows when it is synced
        self._initial_events = initial_events
        # extra request headers (the smart client's X-Kcp-Ring-Epoch
        # stamp on direct-to-shard watches rides here)
        self._extra_headers = extra_headers or {}
        self._session = session
        self._session_cluster = session_cluster
        self.resource = resource
        self._events: asyncio.Queue[Event | None] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._closed = False
        self.error: Exception | None = None  # set on non-2xx watch responses
        self.responded = False  # True once the server sent a status line —
        # lets consumers tell "connect refused" from "established stream
        # died" (the scenario harness's unclean-death accounting)
        self.last_rv = 0  # highest RV seen (events + bookmarks), for resume
        # chunk reassembly state (_feed): decoded-but-incomplete trailing
        # line, and an incremental UTF-8 decoder so each chunk is decoded
        # exactly once — a multi-byte sequence straddling a chunk
        # boundary is carried by the decoder, not re-scanned
        self._buf = ""
        self._decoder = codecs.getincrementaldecoder("utf-8")()

    def _ensure_started(self) -> None:
        if self._task is None and not self._closed:
            self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        reader = writer = None
        try:
            # WAN-link realism: a peer-scoped partition cuts the stream at
            # connect time exactly like a refused connection (the informer
            # relists against another peer or backs off); link.delay adds
            # the configured one-way latency before the connect
            delay = link_fault(self.link_src, f"{self._host}:{self._port}")
            if delay:
                await asyncio.sleep(delay)
            reader, writer = await asyncio.open_connection(
                self._host, self._port, ssl=self._ssl,
                server_hostname=self._host if self._ssl else None)
            auth = (f"Authorization: Bearer {self._token}\r\n"
                    if self._token else "")
            extra = "".join(f"{k}: {v}\r\n"
                            for k, v in self._extra_headers.items())
            writer.write(
                f"GET {self._path} HTTP/1.1\r\nHost: {self._host}\r\n"
                f"{auth}{extra}Connection: close\r\n\r\n".encode())
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            status_line = head.split(b"\r\n", 1)[0].decode("latin-1")
            code = int(status_line.split(" ")[1])
            self.responded = True
            if code >= 400:
                body = await reader.read(64 * 1024)
                # response headers ride the error (err.http_headers) so
                # a direct-to-shard watch refusal keeps its ring-epoch
                # stamp, exactly like the request path
                hdrs: dict[str, str] = {}
                for hline in head.split(b"\r\n")[1:]:
                    if b":" in hline:
                        hk, _, hv = hline.partition(b":")
                        hdrs[hk.decode("latin-1").strip().lower()] = \
                            hv.decode("latin-1").strip()
                # strip chunked framing if present; _raise_for_status just
                # needs the JSON Status body
                try:
                    _raise_for_status(
                        code, body[body.find(b"{"):body.rfind(b"}") + 1],
                        headers=hdrs)
                except errors.ApiError as e:
                    self.error = e
                return
            while True:
                if should_drop("watch"):
                    # injected stream loss (KCP_FAULTS `watch:drop...`):
                    # die mid-stream like a dropped connection — the
                    # informer's reflector loop re-lists and re-watches
                    break
                size_line = await reader.readline()
                if not size_line:
                    break
                size = int(size_line.strip() or b"0", 16)
                if size == 0:
                    break
                chunk = await reader.readexactly(size)
                await reader.readexactly(2)  # trailing \r\n
                self._feed(chunk)
        except (ConnectionError, asyncio.IncompleteReadError, OSError,
                ValueError, IndexError):
            pass  # connection died or stream garbled → clean end-of-stream
        finally:
            if writer is not None:
                writer.close()
            self._closed = True
            self._events.put_nowait(None)

    def _feed(self, chunk: bytes) -> None:
        """Reassemble one chunk payload into complete event lines.

        The chunk is decoded to ``str`` exactly once and split in one
        pass; ``json.loads`` then parses ready text instead of
        re-detecting and re-decoding bytes per line (the server's relay
        batches event bursts into multi-line chunks, so a chunk commonly
        carries many events). The incomplete trailing line — and any
        multi-byte UTF-8 sequence the chunk boundary split — carries
        over to the next chunk."""
        lines = (self._buf + self._decoder.decode(chunk)).split("\n")
        self._buf = lines.pop()  # partial trailing line (usually empty)
        for line in lines:
            if line.strip():
                self._handle_line(json.loads(line))

    def _handle_line(self, msg: dict) -> None:
        if msg.get("type") == "ERROR":
            obj = msg.get("object") or {}
            code = obj.get("code", 410)
            reason = obj.get("reason", "")
            message = obj.get("message", "watch window expired")
            if code == 410 or reason == "Expired":
                # 410 Gone — watch window expired. Typed GoneError (a
                # ConflictError subclass, matching the in-process Watch)
                # so consumers re-list NOW instead of backoff-retrying a
                # watch that can never be served.
                self.error = errors.GoneError(message)
            else:
                # a relayed backend refusal (403 bad store token, 404,
                # 429 throttling, ...): carry the real taxonomy so
                # callers don't relist forever against a watch that can
                # never be served — and so 429s keep their pacing hint
                self.error = _status_error(code, reason, message,
                                           details=obj.get("details"))
            self._closed = True
            self._events.put_nowait(None)
            return
        if msg.get("type") == "BOOKMARK":
            # progress marker: remember the RV for resume, emit nothing —
            # EXCEPT the watch-list sync marker, which the consumer needs
            # to see to know its initial ADDED stream is complete
            meta = (msg.get("object") or {}).get("metadata") or {}
            try:
                rv = int(meta.get("resourceVersion", "0"))
                self.last_rv = rv
            except ValueError:
                rv = 0
            if self._session is not None:
                self._session.note(self._session_cluster, rv)
            if (self._initial_events and (meta.get("annotations") or {})
                    .get(INITIAL_EVENTS_END) == "true"):
                self._events.put_nowait(Event(
                    type="BOOKMARK", resource=self.resource, cluster="",
                    namespace="", name="", object=msg.get("object") or {},
                    rv=rv))
            return
        obj = msg["object"]
        meta = obj.get("metadata") or {}
        rv = int(meta.get("resourceVersion", "0"))
        self.last_rv = max(self.last_rv, rv)
        if self._session is not None:
            self._session.note(self._session_cluster
                               or meta.get("clusterName", ""), rv)
        self._events.put_nowait(Event(
            type=msg["type"],
            resource=self.resource,
            cluster=meta.get("clusterName", ""),
            namespace=meta.get("namespace", ""),
            name=meta.get("name", ""),
            object=obj,
            rv=rv,
        ))

    def __aiter__(self) -> "RestWatch":
        self._ensure_started()
        return self

    async def __anext__(self) -> Event:
        self._ensure_started()
        if self._closed and self._events.empty():
            self._raise_if_error()
            raise StopAsyncIteration
        ev = await self._events.get()
        if ev is None:
            # keep the sentinel so repeated iteration keeps terminating
            self._events.put_nowait(None)
            self._raise_if_error()
            raise StopAsyncIteration
        return ev

    def _raise_if_error(self) -> None:
        if self.error is not None:
            err, self.error = self.error, None
            raise err

    async def next_batch(self, max_wait: float = 0.05) -> list[Event]:
        self._ensure_started()
        out: list[Event] = []
        if self._closed and self._events.empty():
            self._raise_if_error()
            return out
        try:
            ev = await asyncio.wait_for(self._events.get(), timeout=max_wait)
            if ev is None:
                self._events.put_nowait(None)
                self._raise_if_error()
                return out
            out.append(ev)
        except asyncio.TimeoutError:
            return out
        out.extend(self.drain())
        return out

    def drain(self) -> list[Event]:
        out: list[Event] = []
        while not self._events.empty():
            ev = self._events.get_nowait()
            if ev is None:
                self._events.put_nowait(None)
                break
            out.append(ev)
        return out

    def pending(self) -> int:
        """Buffered event count (may include the end-of-stream sentinel);
        part of the Watch duck type — the handler's watch streamer emits
        bookmarks only when a watch has nothing pending."""
        return self._events.qsize()

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None


class RestClient:
    """HTTP twin of :class:`kcp_tpu.client.Client`."""

    # source name for peer-scoped link faults; harnesses that model a
    # specific vantage point (a router relay pool, a syncer) override it
    link_src = "client"

    def __init__(self, base_url: str, cluster: str = "admin",
                 scheme: Scheme | None = None, token: str = "",
                 ca_data: bytes | str | None = None,
                 ca_file: str | None = None):
        parts = urlsplit(base_url)
        self._host = parts.hostname or "127.0.0.1"
        self._tls = parts.scheme == "https"
        self._port = parts.port or (443 if self._tls else 80)
        self.base_url = base_url.rstrip("/")
        self.cluster = cluster
        self.scheme = scheme if scheme is not None else default_scheme()
        self.token = token  # bearer credential (RBAC-lite servers)
        self.ca_data = ca_data  # PEM trust anchor for the server's CA
        self.ca_file = ca_file
        self._ssl = None
        if self._tls:
            from .certs import client_context

            self._ssl = client_context(ca_data, ca_file)
        self._discovered: dict[str, ResourceInfo] = {}
        # _discovered is SHARED across every scoped() clone (a cheap
        # process-wide discovery cache), and RemoteStore's per-cluster
        # store-pool threads refresh it concurrently — guard it with an
        # explicit lock instead of relying on the GIL making dict ops
        # atomic (ADVICE r5). The lock is shared by the clones too;
        # refreshes run under it on the caller's own connection, so
        # holding it never waits on another client's in-flight verb.
        self._disc_lock = make_lock("rest.discovery")
        # circuit breaker per peer, SHARED by every scoped() clone (like
        # the discovery cache): a dead backend trips once and every
        # cluster-scoped client fails fast instead of each burning its
        # own 30s connect timeouts on the store-I/O executor
        self._breaker = CircuitBreaker(f"rest_{self._host}_{self._port}")
        self._conn: http.client.HTTPConnection | None = None
        # session read-your-writes floor (KCP_SESSION_RV), shared across
        # scoped() clones via the __dict__ copy; None when disabled
        self._session = _SessionRv() if _session_rv_enabled() else None

    def scoped(self, cluster: str) -> "RestClient":
        # type(self), not RestClient: a subclass's scoped clones keep the
        # subclass behavior (a SmartRestClient's clones must keep routing
        # direct — the shared ring state rides the __dict__ copy)
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)  # _discovered + _disc_lock shared
        c.cluster = cluster
        c._conn = None  # connections are per-instance; ssl ctx is shared
        return c

    # ------------------------------------------------------------ plumbing

    def _roundtrip(self, method: str, path: str, payload: bytes | None,
                   headers: dict[str, str]):
        """One request over a kept-alive connection; returns
        ``(status, response, body bytes)`` — the already-read response
        object is kept only for header access — without interpreting the
        status: the JSON verbs raise through :func:`_raise_for_status`,
        the shard router relays status/headers/body verbatim.

        Retry discipline: a send-stage failure on a *reused* connection is
        the classic stale-keep-alive case and is safe to retry for any
        method (the request never reached the server). A failure while
        reading the response is only retried for GET — the server may have
        already committed a POST/PUT/DELETE, and re-sending would duplicate
        the write.

        Degraded-mode I/O: the per-peer circuit breaker fails fast
        (UnavailableError) while the peer is known-dead, counting only
        transport failures that actually propagate — a stale keep-alive
        recovered by the retry is not a dead peer, and an HTTP error
        status is the peer answering. ``rest.request`` is a KCP_FAULTS
        injection point (error/latency).
        """
        self._breaker.check()
        try:
            delay = maybe_fail("rest.request")
            # WAN-link realism: a peer-scoped partition toward this
            # server raises ConnectionError exactly where a refused
            # connect would; link.delay models the one-way wire latency
            delay += link_fault(self.link_src, f"{self._host}:{self._port}")
        except Exception:
            # injected transport failure: feed the breaker so chaos
            # schedules exercise the open/half-open transitions
            self._breaker.record_failure()
            raise
        if delay:
            time.sleep(delay)
        for attempt in (0, 1):
            reused = self._conn is not None
            if self._conn is None:
                if self._tls:
                    self._conn = http.client.HTTPSConnection(
                        self._host, self._port, timeout=30, context=self._ssl)
                else:
                    self._conn = http.client.HTTPConnection(
                        self._host, self._port, timeout=30)
            try:
                self._conn.request(method, path, body=payload, headers=headers)
            except (ConnectionError, http.client.HTTPException, OSError):
                self._conn.close()
                self._conn = None
                if reused and attempt == 0:
                    continue
                self._breaker.record_failure()
                raise
            try:
                resp = self._conn.getresponse()
                data = resp.read()
            except (ConnectionError, http.client.HTTPException, OSError):
                self._conn.close()
                self._conn = None
                if method == "GET" and attempt == 0:
                    continue
                self._breaker.record_failure()
                raise
            self._breaker.record_success()
            return resp.status, resp, data
        raise AssertionError("unreachable")

    def _request(self, method: str, path: str, body: dict | None = None) -> dict | None:
        """One JSON verb round trip (see :meth:`_roundtrip` for the retry
        and circuit-breaker discipline); raises the mapped ApiError on
        HTTP error statuses.

        Tracing: with KCP_TRACE on, the request carries a ``traceparent``
        header — the current context's child when one is installed (a
        traced caller, e.g. a syncer apply), else a freshly minted
        head-sampled root; sampled round trips record a
        ``client.request`` span. KCP_TRACE=0 skips even the header, so
        the wire is byte-identical to the pre-tracing client."""
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if method == "GET" and self._session is not None:
            # session read-your-writes: stamp the per-cluster floor so
            # a replica serves this read no staler than the session's
            # own writes/watch position (headers are built before
            # _roundtrip, so the smart client's direct path rides this
            # unchanged)
            floor = self._session.floor(_path_cluster(path))
            if floor:
                headers["X-Kcp-Min-Rv"] = str(floor)
        tracer = obs.TRACER
        sub = t0 = None
        if tracer.enabled:
            ctx = obs.current()
            if ctx is None and tracer.head_sampled():
                ctx = tracer.mint(sampled=True)
            if ctx is not None and ctx.sampled:
                sub = tracer.child(ctx)
                headers[obs.TRACEPARENT] = sub.header()
                t0 = time.time()
            elif ctx is not None:
                # a traced-but-unsampled caller still propagates, so a
                # downstream SLO force-record shares its trace id
                headers[obs.TRACEPARENT] = ctx.header()
        status, resp, data = self._roundtrip(method, path, payload, headers)
        if sub is not None:
            obs.record_span(
                "client.request", sub, ctx.span_id, t0, time.time() - t0,
                {"method": method, "path": path.partition("?")[0][:160],
                 "status": status})
        retry_after = None
        rheaders = None
        if status >= 400:
            # error responses keep their headers on the raised ApiError
            # (err.http_headers): Retry-After pacing and the shard's
            # X-Kcp-Ring-Epoch stamp must survive the raise so the smart
            # client's fallback sees them on the direct path too
            rheaders = {k.lower(): v for k, v in resp.getheaders()}
            if status in (429, 503, 504):
                # a throttling/shedding answer is the peer ALIVE (the
                # breaker saw record_success above); surface the pacing
                # hint instead
                try:
                    retry_after = float(rheaders.get("retry-after") or "")
                except ValueError:
                    pass
        _raise_for_status(status, data, retry_after=retry_after,
                          headers=rheaders)
        out = json.loads(data) if data else None
        if (self._session is not None
                and method in ("POST", "PUT", "DELETE")):
            # raise the session floor from the write's committed RV:
            # X-Kcp-Rv header (covers delete Status bodies), else the
            # object's own metadata.resourceVersion
            geth = getattr(resp, "getheaders", None)
            rv = (next((v for k, v in geth()
                        if k.lower() == "x-kcp-rv"), None)
                  if geth is not None else None)
            if rv is None and isinstance(out, dict):
                rv = (out.get("metadata") or {}).get("resourceVersion")
            self._session.note(_path_cluster(path), rv)
        return out

    def request_raw(self, method: str, target: str,
                    payload: bytes | None = None,
                    headers: dict[str, str] | None = None,
                    ) -> tuple[int, dict[str, str], bytes]:
        """Raw relay round trip for proxies (the shard router): the
        caller's target/body/headers go over the wire verbatim and the
        response ``(status, headers, body)`` comes back uninterpreted —
        HTTP error statuses are the peer ANSWERING and are relayed, not
        raised. Transport failures and an open circuit breaker still
        raise (the router maps those to a fail-fast 503). This client's
        configured bearer token is added only when the caller forwarded
        no Authorization of its own."""
        h = dict(headers or {})
        if self.token and not any(k.lower() == "authorization" for k in h):
            h["Authorization"] = f"Bearer {self.token}"
        status, resp, data = self._roundtrip(method, target, payload, h)
        return status, dict(resp.getheaders()), data

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _resolve(self, resource: str) -> ResourceInfo:
        info = self.scheme.by_resource(resource)
        if info is not None:
            return info
        with self._disc_lock:
            info = self._discovered.get(resource)
        if info is not None:
            return info
        self._refresh_discovery()
        with self._disc_lock:
            info = self._discovered.get(resource)
        if info is None:
            raise errors.NotFoundError(f"resource {resource} not served")
        return info

    def _refresh_discovery(self) -> None:
        """Populate the resource→GVR map from /api + /apis discovery.

        The HTTP walk runs unlocked (on this client's own connection);
        the shared map is swapped in one locked merge so concurrent
        store-pool refreshes never interleave partial states."""
        gvs: list[tuple[str, str]] = [("", "v1")]
        groups = self._request("GET", "/apis") or {}
        for g in groups.get("groups", []):
            for v in g.get("versions", []):
                gvs.append((g["name"], v["version"]))
        found: dict[str, ResourceInfo] = {}
        for group, version in gvs:
            prefix = f"/apis/{group}/{version}" if group else f"/api/{version}"
            try:
                rlist = self._request("GET", prefix) or {}
            except errors.ApiError:
                continue
            for r in rlist.get("resources", []):
                if "/" in r["name"]:
                    continue
                gvr = GVR(group, version, r["name"])
                found[gvr.storage_name] = ResourceInfo(
                    gvr=gvr, kind=r["kind"], list_kind=r["kind"] + "List",
                    singular=r.get("singularName") or r["kind"].lower(),
                    namespaced=bool(r.get("namespaced")),
                )
        with self._disc_lock:
            self._discovered.update(found)

    def _path(self, resource: str, namespace: str | None, name: str | None = None,
              subresource: str | None = None, cluster: str | None = None,
              query: str = "") -> str:
        info = self._resolve(resource)
        gvr = info.gvr
        prefix = f"/apis/{gvr.group}/{gvr.version}" if gvr.group else f"/api/{gvr.version}"
        p = f"/clusters/{quote(cluster or self.cluster, safe='*')}" + prefix
        if namespace:
            p += f"/namespaces/{namespace}"
        p += f"/{gvr.resource}"
        if name:
            p += f"/{name}"
        if subresource:
            p += f"/{subresource}"
        if query:
            p += "?" + query
        return p

    @staticmethod
    def _resource_name(gvr: GVR | str) -> str:
        return gvr.storage_name if isinstance(gvr, GVR) else gvr

    # -------------------------------------------------------------- reads

    def get(self, gvr: GVR | str, name: str, namespace: str = "") -> dict:
        res = self._resource_name(gvr)
        return self._request("GET", self._path(res, namespace, name))

    # paged list iteration is transparent, so informers relist in
    # bounded pages — and servers that page can also watch-list
    supports_watch_list = True

    def list(self, gvr: GVR | str, namespace: str | None = None,
             selector: LabelSelector | None = None,
             limit: int | None = None) -> tuple[list[dict], int]:
        """List, paging transparently (KEP-365): ``KCP_LIST_PAGE``
        (default 10000) bounds how much any one response buffers;
        ``limit`` overrides per call; ``0`` restores the legacy one-shot
        list. The returned RV is the first page's pin — every follow-up
        page is served *at that RV*, so the concatenation is exactly the
        one-shot list. A continue token that outlives the server's watch
        window answers 410: the chunked list restarts from scratch once,
        then propagates."""
        res = self._resource_name(gvr)
        base_q = []
        if selector is not None and not selector.empty:
            base_q.append("labelSelector=" + quote(str(selector)))
        page = _list_page_size() if limit is None else limit
        if page <= 0:
            body = self._request(
                "GET", self._path(res, namespace, query="&".join(base_q)))
            rv = int((body.get("metadata") or {})
                     .get("resourceVersion", "0"))
            return body.get("items", []), rv
        items: list[dict] = []
        rv = 0
        cont = ""
        restarted = False
        while True:
            q = list(base_q) + [f"limit={page}"]
            if cont:
                q.append("continue=" + quote(cont, safe=""))
            try:
                body = self._request(
                    "GET", self._path(res, namespace, query="&".join(q)))
            except errors.GoneError:
                if not cont or restarted:
                    raise
                items, cont, rv, restarted = [], "", 0, True
                continue
            meta = body.get("metadata") or {}
            if not cont:
                rv = int(meta.get("resourceVersion", "0"))
            items.extend(body.get("items", []))
            cont = meta.get("continue") or ""
            if not cont:
                return items, rv

    def watch(self, gvr: GVR | str, namespace: str | None = None,
              selector: LabelSelector | None = None,
              since_rv: int | None = None,
              bookmarks: bool = True,
              initial_events: bool = False) -> RestWatch:
        """Open a watch stream. ``bookmarks`` (default on, KEP-1904
        style) asks the server for periodic BOOKMARK progress markers:
        RestWatch absorbs them into ``last_rv`` without yielding, so a
        stream dropped after a quiet period resumes from a fresh RV
        inside the watch window instead of 410ing into a relist.
        ``initial_events`` (KEP-3157 style) asks the server to stream
        the current state as ADDED events first, ending with a sync
        BOOKMARK that RestWatch *yields* — list+watch in one stream,
        never holding a whole list body (``since_rv`` must be None)."""
        res = self._resource_name(gvr)
        query = "watch=true"
        if selector is not None and not selector.empty:
            query += "&labelSelector=" + quote(str(selector))
        if since_rv is not None:
            query += f"&resourceVersion={since_rv}"
        if bookmarks:
            query += "&allowWatchBookmarks=true"
        if initial_events:
            query += "&sendInitialEvents=true"
        path = self._path(res, namespace, query=query)
        return RestWatch(self._host, self._port, path, res, token=self.token,
                         ssl_context=self._ssl,
                         initial_events=initial_events,
                         session=self._session,
                         session_cluster=(self.cluster
                                          if self.cluster != WILDCARD
                                          else ""))

    # ------------------------------------------------------------- writes

    def _write_cluster(self, obj: dict) -> str:
        return resolve_write_cluster(self.cluster, obj)

    def create(self, gvr: GVR | str, obj: dict, namespace: str = "") -> dict:
        res = self._resource_name(gvr)
        namespace = namespace or (obj.get("metadata") or {}).get("namespace", "")
        return self._request(
            "POST", self._path(res, namespace, cluster=self._write_cluster(obj)), obj)

    def update(self, gvr: GVR | str, obj: dict, namespace: str = "") -> dict:
        res = self._resource_name(gvr)
        meta = obj.get("metadata") or {}
        namespace = namespace or meta.get("namespace", "")
        return self._request(
            "PUT",
            self._path(res, namespace, meta["name"], cluster=self._write_cluster(obj)),
            obj)

    def update_status(self, gvr: GVR | str, obj: dict, namespace: str = "") -> dict:
        res = self._resource_name(gvr)
        meta = obj.get("metadata") or {}
        namespace = namespace or meta.get("namespace", "")
        return self._request(
            "PUT",
            self._path(res, namespace, meta["name"], "status",
                       cluster=self._write_cluster(obj)),
            obj)

    def delete(self, gvr: GVR | str, name: str, namespace: str = "",
               cluster: str | None = None) -> None:
        res = self._resource_name(gvr)
        target = cluster or self.cluster
        if target == WILDCARD:
            raise errors.InvalidError("wildcard delete requires an explicit cluster")
        self._request("DELETE", self._path(res, namespace, name, cluster=target))

    # ---------------------------------------------------------- discovery

    def resources(self) -> list[str]:
        self._refresh_discovery()
        with self._disc_lock:
            discovered = set(self._discovered)
        return sorted(discovered |
                      {i.gvr.storage_name for i in self.scheme.all()})

    def openapi_v2(self) -> dict | None:
        """Fetch the server's ``/openapi/v2`` document (None on 404)."""
        try:
            return self._request(
                "GET", f"/clusters/{quote(self.cluster, safe='*')}/openapi/v2")
        except errors.NotFoundError:
            return None


class MultiClusterRestClient(RestClient):
    """Wildcard RestClient (EnableMultiCluster analog over the wire)."""

    def __init__(self, base_url: str, scheme: Scheme | None = None,
                 token: str = "", ca_data: bytes | str | None = None,
                 ca_file: str | None = None):
        super().__init__(base_url, WILDCARD, scheme, token=token,
                         ca_data=ca_data, ca_file=ca_file)

    def cluster_client(self, cluster: str) -> RestClient:
        return self.scoped(cluster)
