"""Server core: wires storage + REST surface + in-process controllers.

The analog of the reference's pkg/server/server.go:79-292: create the
data dir, bring up storage (WAL-backed LogicalStore standing in for
embedded etcd, reference pkg/etcd/etcd.go), serve the REST API, write
admin.kubeconfig (server.go:151-176), then fire post-start hooks that
install the in-process controllers (the "Install Cluster Controller"
hook, server.go:193-255).
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass, field

from ..apis.scheme import Scheme, default_scheme
from ..client import MultiClusterClient
from ..physical import PhysicalRegistry
from ..store import LogicalStore
from .handler import RestHandler, render_kubeconfig
from .httpd import HttpServer

log = logging.getLogger(__name__)


@dataclass
class Config:
    """Server configuration (reference: pkg/server/config.go:13-42)."""

    root_dir: str = ".kcp_tpu"
    listen_host: str = "127.0.0.1"
    listen_port: int = 0  # 0 = ephemeral (reference default is 6443)
    durable: bool = True  # WAL-backed store vs in-memory
    store_server: str = ""  # external-storage option (the reference's
    # kcp start --etcd-servers, server.go:263-291): serve against another
    # kcp-tpu server's storage over REST instead of embedding a store.
    # Durability and storage semantics belong to that backend; run
    # controllers on exactly one process.
    store_token: str = ""  # bearer token for an authz'd storage backend
    store_ca_file: str | None = None  # CA for a TLS storage backend
    install_controllers: bool | None = None  # in-proc controllers.
    # None = auto: True for an embedded store (kcp start default), False
    # when store_server is set — in-process controllers issue BLOCKING
    # RemoteStore HTTP calls (30 s timeout each) straight on the serving
    # loop via MultiClusterClient, bypassing the handler's store-I/O
    # thread pool: a slow backend freezes watches and /healthz. An
    # explicit True with store_server is a hard error unless
    # force_remote_controllers acknowledges the hazard.
    force_remote_controllers: bool = False  # accept loop-blocking remote
    # controllers (and the controller-fighting risk) with store_server
    auto_publish_apis: bool = False  # --auto_publish_apis flag analog
    resources_to_sync: list[str] = field(default_factory=lambda: ["deployments.apps"])
    syncer_mode: str = "push"  # push | pull | none (controller.go:42-48)
    syncer_image: str = ""  # pull-mode image the installer deploys
    # (contrib/syncer-image/Dockerfile; reference: the cluster
    # controller's syncer-image flag). Empty = installer.
    # DEFAULT_SYNCER_IMAGE — resolved at wiring time to keep the one
    # definition in installer.py
    poll_interval: float = 15.0
    import_poll_interval: float = 15.0
    authz: bool = False  # RBAC-lite enforcement (server/authz.py); the
    # reference prototype runs open, so open stays the default
    admin_token: str = ""  # minted when empty and authz is on
    tls: bool = True  # serve HTTPS with self-generated certs (reference
    # parity: pkg/etcd/etcd.go:98-188 + server.go:151-176); certs persist
    # under root_dir/pki for durable servers, ephemeral otherwise
    mesh: str = ""  # serving-mesh spec ("8", "4x2", "2x2x2"): shard the
    # fused reconcile core's buckets over a jax device mesh (SURVEY §7.2
    # step 9; the reference's horizontal-sharding story,
    # docs/investigations/logical-clusters.md:83)
    pallas: bool = False  # serve the fused Pallas decide+match kernel
    # (ops/pallas_kernels.py) instead of the XLA lanes (single-device)
    role: str = "shard"  # shard (a normal server — the default) | router
    # (the sharded control plane's scatter-gather frontend: no storage,
    # no controllers; every request routes over the shard ring) |
    # replica (read-only follower fed by a primary's WAL feed, serving
    # GET/LIST/WATCH RV-honestly from its own store + encode cache) |
    # standby (a replica that promotes itself to primary when the
    # primary's breaker stays open past the hysteresis window)
    shards: str = ""  # router role: comma-separated [name=]url shard list
    # (KCP_SHARDS env is the fallback; see kcp_tpu/sharding/ring.py)
    shard_name: str = ""  # shard role: this server's stable name in the
    # ring (KCP_SHARD_NAME env fallback). With ring_names set, direct
    # smart-client requests (X-Kcp-Ring-Epoch stamped) are verified
    # against HRW ownership: a stale-ring client gets a typed 410
    # instead of a silently-wrong shard's answer
    ring_names: str = ""  # shard role: comma-separated names of EVERY
    # shard in the ring (KCP_RING_NAMES env fallback) — names alone
    # determine HRW ownership, so a shard can verify direct requests
    # without knowing anyone's address
    ring_epoch: int = 0  # shard role: the ring epoch this shard was
    # (re)started under; stamped on ring-mismatch 410s so smart clients
    # can tell a stale shard from a stale self
    primary: str = ""  # replica/standby roles: the primary's base URL
    # (the /replication/wal feed source and the health-probe target).
    # Accepts a comma-separated CANDIDATE list ("url1,url2"): a replica
    # whose current primary stays dead or fenced past the hysteresis
    # window probes the candidates in order and re-homes onto whichever
    # one serves as the live primary (the promoted standby after a
    # failover). KCP_PRIMARY env is the fallback for the flag.
    drain_timeout_s: float | None = None  # graceful-drain budget for
    # Server.drain (None -> KCP_DRAIN_TIMEOUT_S, default 5.0): the wall
    # bound on stop-accepting + finish-in-flight + terminal watch
    # Status + replication flush; whatever is still alive at the
    # deadline is cut off hard
    repl_hysteresis_s: float | None = None  # standby promotion: how long
    # the primary's breaker must stay open before the standby promotes
    # (None -> KCP_REPL_HYSTERESIS_S, default 3.0s). Too low and a slow
    # GC pause triggers a split brain race the fence then has to win;
    # too high and writes are down that much longer.
    repl_lag_max: int | None = None  # replicas refuse reads 503 past
    # this many records of lag (None -> KCP_REPL_LAG_MAX, default 0 =
    # serve any staleness RV-honestly)
    fleet: bool = False  # fleet placement control plane (KCP_FLEET=1 env
    # fallback): a FleetScheduler takes over the DeploymentSplitter's
    # placement decision with the capacity/locality-aware batched
    # bin-pack (kcp_tpu/fleet/). Spread + locality weight come from
    # KCP_FLEET_SPREAD / KCP_FLEET_LOCALITY_WEIGHT.


class Server:
    """One kcp-tpu control-plane process."""

    def __init__(self, config: Config | None = None, scheme: Scheme | None = None,
                 registry: PhysicalRegistry | None = None):
        self.config = config or Config()
        self.scheme = scheme or default_scheme()
        self.registry = registry or PhysicalRegistry()
        # resolve the install_controllers tri-state once (see Config):
        # frontends serving someone else's storage default to serve-only,
        # and a router (no storage at all) can never run controllers
        # routers own no storage; replicas/standbys serve a replicated
        # store that in-process controllers would fight the primary's
        # controllers over — none of the three may run controllers
        self.install_controllers = (
            False if self.config.role in ("router", "replica", "standby")
            else self.config.install_controllers
            if self.config.install_controllers is not None
            else not self.config.store_server)
        self.repl_hub = None
        self.repl_applier = None
        if self.config.role == "router":
            # scatter-gather frontend over a shard ring: no store, no
            # controllers — requests relay to the owning shard(s). Authz
            # is terminated BY THE SHARDS (bearer tokens pass through);
            # enforcing it here too would need the router to share the
            # shards' role objects it deliberately does not store.
            from ..sharding import RouterHandler, ShardRing

            if self.config.authz:
                raise ValueError(
                    "--authz with --role router: the router does not "
                    "terminate authz — shards enforce it on every relayed "
                    "request; run the router open and pass bearer tokens "
                    "through")
            if self.config.store_server:
                raise ValueError("--store-server with --role router: a "
                                 "router routes to --shards, not to a "
                                 "storage backend")
            ring = (ShardRing.from_spec(self.config.shards,
                                        os.environ.get("KCP_REPLICAS", ""))
                    if self.config.shards else ShardRing.from_env())
            self.store = None
            self.authenticator = None
            self.handler = RouterHandler(
                ring, token=self.config.store_token,
                ca_file=self.config.store_ca_file)
            self.certs = None
            ssl_context = None
            if self.config.durable:
                # no WAL, but start() still renders admin.kubeconfig (and
                # TLS persists pki/) under root_dir
                os.makedirs(self.config.root_dir, exist_ok=True)
            if self.config.tls:
                from .certs import ServingCerts

                cert_dir = (os.path.join(self.config.root_dir, "pki")
                            if self.config.durable else None)
                hosts = {self.config.listen_host, "127.0.0.1", "localhost"}
                self.certs = ServingCerts.load_or_create(cert_dir,
                                                         sorted(hosts))
                ssl_context = self.certs.server_context()
            self.http = HttpServer(self.handler, self.config.listen_host,
                                   self.config.listen_port,
                                   ssl_context=ssl_context)
            self.client = None
            self._controllers = []
            self._post_start_hooks = []
            self._stop = asyncio.Event()
            return
        if self.config.role in ("replica", "standby"):
            if not self.config.primary:
                # KCP_PRIMARY env is the flag's fallback (and carries the
                # same comma-separated candidate-list form)
                self.config.primary = os.environ.get("KCP_PRIMARY", "")
            if not self.config.primary:
                raise ValueError(
                    f"--role {self.config.role} needs --primary (the "
                    f"primary server's base URL to follow)")
            if self.config.store_server:
                raise ValueError(
                    "--store-server with --role replica/standby: a "
                    "follower replays the primary's WAL into its OWN "
                    "store; it cannot also delegate storage elsewhere")
        if self.config.store_server:
            # external storage: this process is a stateless frontend; the
            # backend's store owns RVs, conflicts, finalizers, and the WAL
            from ..store.remote import RemoteStore

            if self.config.durable:
                # no WAL here, but start() still writes admin.kubeconfig
                # (and TLS persists pki/) under root_dir
                os.makedirs(self.config.root_dir, exist_ok=True)
            if self.install_controllers:
                if not self.config.force_remote_controllers:
                    # hard error, not a warning (ADVICE r5): in-process
                    # controllers run their RemoteStore HTTP verbs (30 s
                    # timeouts) directly on the serving loop — a slow or
                    # unreachable backend freezes watches and /healthz —
                    # on top of frontend/backend controllers fighting
                    # over the shared dataset
                    raise ValueError(
                        "install_controllers=True with store_server would "
                        "run controllers that issue blocking remote-store "
                        "HTTP calls on the serving loop (and fight any "
                        "backend-side controllers over the shared "
                        "dataset); run controllers on the storage backend "
                        "instead, or set force_remote_controllers=True "
                        "(--force-install-controllers) if you accept both "
                        "hazards")
                log.warning(
                    "--store-server with in-process controllers (forced): "
                    "a slow storage backend can block the serving loop, "
                    "and the backend (or any other frontend) must NOT "
                    "also be running controllers")
            self.store = RemoteStore(self.config.store_server,
                                     token=self.config.store_token,
                                     ca_file=self.config.store_ca_file)
        else:
            wal = None
            if self.config.durable:
                os.makedirs(self.config.root_dir, exist_ok=True)
                wal = os.path.join(self.config.root_dir, "store.wal")
            # finalizer stamping is only safe when the namespace
            # controller that releases it will run (install_controllers)
            self.store = LogicalStore(
                wal_path=wal,
                namespace_lifecycle=self.install_controllers,
            )
        authn = authz = None
        if self.config.authz:
            import secrets as _secrets

            from .authz import ADMIN_USER, Authenticator, Authorizer

            if not self.config.admin_token:
                self.config.admin_token = _secrets.token_urlsafe(24)
            authn = Authenticator(tokens={self.config.admin_token: ADMIN_USER})
            authz = Authorizer(self.store)
        self.authenticator = authn
        self.handler = RestHandler(
            self.store, self.scheme, authenticator=authn, authorizer=authz,
            # a replica never serves a write (the store refuses them
            # anyway), so its admission chain would be dead weight; a
            # standby keeps the default chain for life after promotion
            admission=(None if self.config.role == "replica" else "auto"))
        # smart-client ring identity (env fallbacks let subprocess fleets
        # configure shards without new flags in every harness)
        shard_name = (self.config.shard_name
                      or os.environ.get("KCP_SHARD_NAME", ""))
        ring_names = (self.config.ring_names
                      or os.environ.get("KCP_RING_NAMES", ""))
        if shard_name and ring_names:
            names = tuple(n.strip() for n in ring_names.split(",")
                          if n.strip())
            if shard_name not in names:
                raise ValueError(
                    f"--shard-name {shard_name!r} is not in --ring-names "
                    f"{sorted(names)}")
            self.handler.shard_name = shard_name
            self.handler.ring_names = names
            self.handler.ring_epoch = self.config.ring_epoch or int(
                os.environ.get("KCP_RING_EPOCH", "1") or "1")
        self._wire_replication()
        self.certs = None
        ssl_context = None
        if self.config.tls:
            from .certs import ServingCerts

            cert_dir = (os.path.join(self.config.root_dir, "pki")
                        if self.config.durable else None)
            hosts = {self.config.listen_host, "127.0.0.1", "localhost"}
            self.certs = ServingCerts.load_or_create(cert_dir, sorted(hosts))
            ssl_context = self.certs.server_context()
        self.http = HttpServer(self.handler, self.config.listen_host,
                               self.config.listen_port,
                               ssl_context=ssl_context)
        # the in-process client SHARES the serving scheme: controller-
        # registered CRDs (crdlifecycle.py) must be visible to the REST
        # handler, or a CRD created over REST never serves its CRs
        self.client = MultiClusterClient(self.store, scheme=self.scheme)
        self._controllers: list = []
        self._post_start_hooks: list = []
        self._stop = asyncio.Event()

    def _wire_replication(self) -> None:
        """Attach the WAL-shipping hub (every server with a local store
        can feed replicas) and, for replica/standby roles, the applier
        that follows the configured primary."""
        from ..store import LogicalStore

        if not isinstance(self.store, LogicalStore):
            return  # remote-store frontends ship nothing: the backend does
        from ..replication import ReplicationApplier, ReplicationHub

        self.repl_hub = ReplicationHub(self.store)
        self.handler.repl_hub = self.repl_hub
        role = self.config.role
        if role not in ("replica", "standby"):
            return
        self.store.read_only = (
            "replica serves reads only; writes go to the primary"
            if role == "replica"
            else "standby awaiting promotion; writes go to the primary")
        self.store.reject_future_rv = True
        hysteresis = (self.config.repl_hysteresis_s
                      if self.config.repl_hysteresis_s is not None
                      else float(os.environ.get("KCP_REPL_HYSTERESIS_S",
                                                "3.0")))
        lag_max = (self.config.repl_lag_max
                   if self.config.repl_lag_max is not None
                   else int(os.environ.get("KCP_REPL_LAG_MAX", "0")))

        def on_promote() -> None:
            self.handler.repl_role = "primary"
            log.warning("this server is now the PRIMARY (epoch %d)",
                        self.store.epoch)

        self.repl_applier = ReplicationApplier(
            self.store, self.config.primary, role=role,
            token=self.config.store_token,
            ca_file=self.config.store_ca_file,
            hysteresis_s=hysteresis, on_promote=on_promote)
        self.handler.repl_applier = self.repl_applier
        self.handler.repl_role = role
        self.handler.repl_lag_max = lag_max

    def add_post_start_hook(self, hook) -> None:
        """Register an async callable fired once serving (server.go:294-312)."""
        self._post_start_hooks.append(hook)

    @property
    def address(self) -> str:
        return self.http.address

    @property
    def ca_pem(self) -> bytes | None:
        """The serving CA certificate (None when TLS is off) — what a
        client passes as ``RestClient(..., ca_data=...)``."""
        return self.certs.ca_cert_pem if self.certs else None

    async def start(self) -> None:
        """Bring the server up and fire hooks; returns once serving."""
        from ..utils.raceguard import LoopWatchdog

        # stall visibility on the serving loop (the race/sanitizer story's
        # production half): a reconcile blocking the loop past 1s is
        # logged with the offending stacks
        self._watchdog = LoopWatchdog(asyncio.get_running_loop(),
                                      threshold=1.0).start()
        await self.http.start()
        if self.config.durable:
            render_kubeconfig(self.address,
                              os.path.join(self.config.root_dir, "admin.kubeconfig"),
                              token=self.config.admin_token,
                              ca_pem=self.certs.ca_cert_pem if self.certs else None)
        if self.install_controllers:
            await self._install_controllers()
        if self.repl_applier is not None:
            await self.repl_applier.start()
        for hook in self._post_start_hooks:
            await hook(self)
        self.handler.ready = True
        from ..utils.trace import REGISTRY

        REGISTRY.gauge("kcp_up", "1 once post-start hooks completed").set(1)
        if self.config.authz and not self.config.durable:
            # no kubeconfig to carry the minted token: surface it or every
            # external client is locked out at 403
            log.warning("RBAC-lite on without a kubeconfig; admin token: %s",
                        self.config.admin_token)
        log.info("kcp-tpu serving at %s", self.address)

    async def _install_controllers(self) -> None:
        """The "Install Cluster Controller" post-start hook
        (reference: server.go:193-255 — cluster controller Start(2),
        apiresource controller Start(2), plus CRD lifecycle which the
        reference gets from its forked apiextensions apiserver)."""
        from ..reconcilers.apiresource import NegotiationController
        from ..reconcilers.cluster import ClusterController, SyncerMode
        from ..reconcilers.crdlifecycle import CRDLifecycleController
        from ..reconcilers.deployment import DeploymentSplitter
        from ..reconcilers.namespace import NamespaceLifecycleController

        mode = {"push": SyncerMode.PUSH, "pull": SyncerMode.PULL,
                "none": SyncerMode.NONE}[self.config.syncer_mode]
        if self.config.pallas and os.environ.get("KCP_PALLAS") != "1":
            # FusedCore.for_current_loop reads this at construction; the
            # env form also reaches pull-mode pods via their environment
            os.environ["KCP_PALLAS"] = "1"
            self._set_pallas_env = True
        mesh = None
        if self.config.mesh:
            from ..parallel.mesh import set_serving_mesh

            mesh = set_serving_mesh(self.config.mesh)
            self._installed_mesh = mesh
            log.info("serving mesh: %s",
                     dict(zip(mesh.axis_names, mesh.devices.shape)))
        splitter = DeploymentSplitter(self.client)
        self._controllers = [
            NegotiationController(self.client,
                                  auto_publish=self.config.auto_publish_apis),
            CRDLifecycleController(self.client),
            ClusterController(
                self.client, self.registry,
                resources_to_sync=self.config.resources_to_sync,
                mode=mode, poll_interval=self.config.poll_interval,
                import_poll_interval=self.config.import_poll_interval,
                mesh=mesh, mesh_spec=self.config.mesh,
                **({"syncer_image": self.config.syncer_image}
                   if self.config.syncer_image else {}),
            ),
            splitter,
            # the reference's "start-namespace-controller" hook
            # (server.go:325-356)
            NamespaceLifecycleController(self.client),
        ]
        if self.config.fleet or os.environ.get("KCP_FLEET") == "1":
            from ..fleet.scheduler import FleetScheduler

            # must start AFTER the splitter (it shares its informers);
            # the controllers list starts in order
            self._controllers.append(FleetScheduler(splitter, mesh=mesh))
        admission = getattr(self.handler, "admission", None)
        if admission is not None and admission.ledger is not None:
            # quota usage-recount reconciler (admission/quota.py):
            # applies ResourceQuota limit changes (including in-process
            # writes that bypass the REST chain) and periodically repairs
            # ledger drift against the store's true counts
            from ..admission import UsageRecountController

            self._controllers.append(UsageRecountController(
                self.client, admission.ledger, self.store))
            # the fleet batch's device-side per-segment counters feed
            # this ledger (FusedCore forwards them on every collect), so
            # admission accounting rides the fused device batch and the
            # recount loop can skip its host-side walk when they agree
            from ..syncer.core import FusedCore

            FusedCore.set_process_ledger(admission.ledger)
        for c in self._controllers:
            await c.start()

    async def run(self) -> None:
        """start() then block until stop() (reference: server.go:258-260)."""
        await self.start()
        await self._stop.wait()
        await self.shutdown()

    def stop(self) -> None:
        self._stop.set()

    async def drain(self, timeout: float | None = None) -> bool:
        """Graceful drain (the SIGTERM path): stop accepting
        connections, let in-flight requests finish, deliver buffered
        watch events + a terminal in-stream Status to every live
        watcher, flush the replication feed to subscribers, then return
        True — the caller stops the server afterwards. The whole
        sequence is bounded by ``timeout`` (KCP_DRAIN_TIMEOUT_S,
        default 5.0 s): at the deadline, whatever is still alive is cut
        off exactly as a hard stop would. Returns False when the drain
        was aborted (an injected ``server.drain`` fault) and the caller
        should fall straight through to stop().
        """
        from ..faults import maybe_fail
        from ..utils.trace import REGISTRY

        if timeout is None:
            timeout = (self.config.drain_timeout_s
                       if self.config.drain_timeout_s is not None
                       else float(os.environ.get("KCP_DRAIN_TIMEOUT_S",
                                                 "5.0")))
        loop = asyncio.get_running_loop()
        gauge = REGISTRY.gauge(
            "server_draining",
            "1 while a graceful drain is in progress")
        span = REGISTRY.histogram(
            "server_drain_seconds",
            "wall time of one graceful drain (stop accepting -> "
            "in-flight done -> watchers terminated -> replication "
            "flushed)")
        t0 = loop.time()
        deadline = t0 + max(0.0, timeout)
        gauge.set(1)
        try:
            try:
                delay = maybe_fail("server.drain")
            except Exception as e:  # noqa: BLE001 — injected abort
                log.warning("graceful drain aborted (%s); "
                            "escalating to hard stop", e)
                return False
            if delay:
                await asyncio.sleep(delay)
            # 1. stop accepting: listener closed (late connections are
            # refused at connect time), idle keep-alive conns torn down
            self.http.begin_drain()
            # 2. in-flight requests finish (semi-sync repl waits
            # included); watch streams are excluded — they end in step 3
            if not await self.http.wait_requests_idle(deadline):
                log.warning("drain: in-flight requests still running at "
                            "the %.1fs deadline", timeout)
            # 3. flush + terminate watchers and replication subscribers.
            # An open commit window is flushed FIRST (group commit: a
            # reconciler's last writes may still be buffered — their
            # records must ship BEFORE the hub's drain sentinel), then
            # the store's pending fan-out, so the watch producers' final
            # drain() sees every committed event.
            if self.store is not None and hasattr(self.store,
                                                  "_gc_barrier"):
                self.store._gc_barrier()
            if self.store is not None and hasattr(self.store,
                                                  "_flush_events"):
                self.store._flush_events()
            draining = getattr(self.handler, "draining", None)
            if draining is not None:
                draining.set()
            if self.repl_hub is not None:
                self.repl_hub.drain()
            # 4. wait for every connection to wind down; cut off hard at
            # the deadline
            forced = await self.http.finish_drain(deadline)
            if forced:
                log.warning("drain: %d connection(s) cut off at the "
                            "%.1fs deadline", forced, timeout)
            log.info("graceful drain complete in %.3fs", loop.time() - t0)
            return True
        finally:
            span.observe(loop.time() - t0)
            gauge.set(0)

    def kill(self) -> None:
        """Abrupt-death switch (the in-process SIGKILL emulation the
        kill-the-primary drills use): serving stops immediately and the
        shutdown skips WAL compaction — on-disk state is exactly the
        appended log a killed process leaves, which is what restart and
        standby promotion must recover from."""
        self._killed = True
        self._stop.set()

    async def shutdown(self) -> None:
        if getattr(self, "_watchdog", None) is not None:
            self._watchdog.stop()
            self._watchdog = None
        if getattr(self, "_set_pallas_env", False):
            os.environ.pop("KCP_PALLAS", None)
            self._set_pallas_env = False
        if self.repl_applier is not None:
            await self.repl_applier.stop()
            self.repl_applier = None
        for c in reversed(self._controllers):
            await c.stop()
        self._controllers = []
        if getattr(self, "_installed_mesh", None) is not None:
            # clear the process serving mesh so a later server/syncer in
            # this process doesn't inherit stale sharding — but only if
            # OUR mesh is still the installed one (another live server
            # may have replaced it since)
            from ..parallel.mesh import get_serving_mesh, set_serving_mesh

            if get_serving_mesh() is self._installed_mesh:
                set_serving_mesh(None)
            self._installed_mesh = None
        await self.http.stop()
        self.handler.close()
        if self.store is not None:
            if self.config.durable and not getattr(self, "_killed", False):
                self.store.snapshot()
            self.store.close()
