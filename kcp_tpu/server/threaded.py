"""ServerThread: run a Server on its own event loop in a daemon thread.

The embedding primitive for synchronous callers (CLI tools, tests,
benchmarks): the control plane runs like a separate process — its own
loop owns the store — and callers talk to it over HTTP with RestClient,
exactly as the reference's standalone binaries talk to `kcp start`
(reference: cmd/cluster-controller/main.go, cmd/syncer/main.go).
"""

from __future__ import annotations

import asyncio
import threading

from .server import Config, Server


class ServerThread:
    def __init__(self, config: Config | None = None, **server_kwargs):
        self._config = config or Config(durable=False)
        self._server_kwargs = server_kwargs
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._startup_error: BaseException | None = None
        self.server: Server | None = None
        self.address: str = ""

    def start(self, timeout: float = 30.0) -> "ServerThread":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kcp-tpu-server")
        self._thread.start()
        if not self._started.wait(timeout):
            raise RuntimeError("server thread failed to start in time")
        if self._startup_error is not None:
            self._thread.join(timeout=5)
            raise RuntimeError("server startup failed") from self._startup_error
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def main():
            await self.server.start()
            self.address = self.server.address
            self._started.set()
            await self.server._stop.wait()
            await self.server.shutdown()

        try:
            # construct INSIDE the try: a constructor failure (bad
            # config, missing TLS dependency) must surface through
            # _startup_error immediately, not leave start() waiting out
            # its whole timeout on a thread that already died
            self.server = Server(self._config, **self._server_kwargs)
            self._loop.run_until_complete(main())
        except BaseException as e:  # surfaced to start() — not swallowed
            self._startup_error = e
        finally:
            self._loop.close()
            self._started.set()  # unblock start() on failure paths

    @property
    def ca_pem(self) -> bytes | None:
        """The serving CA (None when TLS off) for RestClient(ca_data=...)."""
        return self.server.ca_pem if self.server else None

    def submit(self, coro):
        """Run a coroutine on the server loop, return its result."""
        assert self._loop is not None
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(30)

    def call(self, fn, *args, **kwargs):
        """Run a plain callable on the server loop thread (store access)."""

        async def _wrap():
            return fn(*args, **kwargs)

        return self.submit(_wrap())

    def drain(self, timeout: float | None = None) -> None:
        """Graceful stop (the SIGTERM path for embedded servers): run
        Server.drain on the server loop — stop accepting, finish
        in-flight requests, terminal Status to live watchers, flush
        replication — then stop. Bounded by KCP_DRAIN_TIMEOUT_S."""
        if self._loop is not None and self.server is not None:
            try:
                self.submit(self.server.drain(timeout))
            except Exception:  # noqa: BLE001 — loop already down: a drain
                pass  # racing a kill/stop degrades to the plain stop below
        self.stop()

    def kill(self) -> None:
        """Abrupt stop (SIGKILL emulation for kill drills): no WAL
        compaction, in-flight streams die mid-chunk. See Server.kill."""
        if self._loop is not None and self.server is not None:
            try:
                self._loop.call_soon_threadsafe(self.server.kill)
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def stop(self) -> None:
        if self._loop is not None and self.server is not None:
            try:
                self._loop.call_soon_threadsafe(self.server.stop)
            except RuntimeError:
                pass  # loop already closed: stop() is idempotent (chaos
                # harnesses kill a shard mid-test and the fixture stops
                # every thread again on teardown)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def __enter__(self) -> "ServerThread":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
