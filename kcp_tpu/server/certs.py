"""Self-signed serving certificates for the control plane.

The reference generates an ECDSA CA + client/server certs at startup for
its embedded etcd and serves the API over TLS :6443
(/root/reference/pkg/etcd/etcd.go:98-188 generateClientAndServerCerts;
pkg/server/server.go:151-176 writes a kubeconfig against the secure
endpoint). This module is the kcp-tpu equivalent: an ECDSA P-521 CA
(curve parity with the reference) signing a server certificate with
SANs for the serving hosts, persisted under the server's root dir so
restarts keep the same CA, plus ssl.SSLContext builders for both ends.

Everything uses the ``cryptography`` package — no shelling out.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
import tempfile

CA_NAME = "kcp-tpu-ca"
_ONE_DAY = datetime.timedelta(days=1)
_TEN_YEARS = datetime.timedelta(days=3650)


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import ec

    # P-521: the reference's curve (etcd.go:118 elliptic.P521())
    return ec.generate_private_key(ec.SECP521R1())


def _key_pem(key) -> bytes:
    from cryptography.hazmat.primitives import serialization

    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def generate_ca(common_name: str = CA_NAME) -> tuple[bytes, bytes]:
    """(cert_pem, key_pem) for a self-signed CA."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.x509.oid import NameOID

    key = _new_key()
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + _TEN_YEARS)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .add_extension(
            x509.KeyUsage(digital_signature=True, key_cert_sign=True,
                          crl_sign=True, content_commitment=False,
                          key_encipherment=False, data_encipherment=False,
                          key_agreement=False, encipher_only=False,
                          decipher_only=False),
            critical=True,
        )
        .sign(key, hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.PEM), _key_pem(key)


def generate_server_cert(
    ca_cert_pem: bytes, ca_key_pem: bytes, hosts: list[str],
    common_name: str = "kcp-tpu",
) -> tuple[bytes, bytes]:
    """(cert_pem, key_pem) for a server certificate signed by the CA,
    with DNS/IP SANs for every entry in ``hosts``."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
    ca_key = serialization.load_pem_private_key(ca_key_pem, password=None)
    key = _new_key()
    sans = []
    for h in hosts:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                                    common_name)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + _TEN_YEARS)
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .add_extension(
            x509.ExtendedKeyUsage([ExtendedKeyUsageOID.SERVER_AUTH]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.PEM), _key_pem(key)


class ServingCerts:
    """The server's TLS material: CA + server cert/key on disk.

    ``load_or_create(dir)`` reuses an existing CA across restarts (the
    kubeconfig users hold its cert); with ``dir=None`` an ephemeral
    tempdir is used (in-memory servers).
    """

    def __init__(self, directory: str, ca_cert: bytes, server_cert: bytes,
                 server_key: bytes, _tmp=None):
        self.directory = directory
        self.ca_cert_pem = ca_cert
        self.server_cert_pem = server_cert
        self.server_key_pem = server_key
        self.server_cert_path = os.path.join(directory, "server.crt")
        self.server_key_path = os.path.join(directory, "server.key")
        self.ca_path = os.path.join(directory, "ca.crt")
        self._tmp = _tmp  # keeps an ephemeral tempdir alive
        # the object IS the material: writing happens here so a directly
        # constructed instance and load_or_create agree with the disk
        with open(self.server_cert_path, "wb") as f:
            f.write(server_cert)
        self._write_private(self.server_key_path, server_key)

    _ephemeral: dict[tuple, "ServingCerts"] = {}

    @classmethod
    def load_or_create(cls, directory: str | None,
                       hosts: list[str] | None = None) -> "ServingCerts":
        hosts = hosts or ["127.0.0.1", "localhost"]
        tmp = None
        if directory is None:
            # in-memory servers: one ephemeral CA per process per host
            # set — P-521 keygen is expensive and the material is
            # process-private anyway
            cached = cls._ephemeral.get(tuple(sorted(hosts)))
            if cached is not None:
                return cached
            tmp = tempfile.TemporaryDirectory(prefix="kcp-tpu-certs-")
            directory = tmp.name
        os.makedirs(directory, exist_ok=True)
        ca_crt = os.path.join(directory, "ca.crt")
        ca_key = os.path.join(directory, "ca.key")
        have_crt, have_key = os.path.exists(ca_crt), os.path.exists(ca_key)
        if have_crt != have_key:
            # a half-present CA pair must not silently mint a NEW CA —
            # that would invalidate every issued kubeconfig with no hint
            raise RuntimeError(
                f"CA material in {directory} is incomplete "
                f"(ca.crt {'present' if have_crt else 'missing'}, "
                f"ca.key {'present' if have_key else 'missing'}); restore "
                f"both or remove both to mint a fresh CA")
        if have_crt:
            with open(ca_crt, "rb") as f:
                ca_cert_pem = f.read()
            with open(ca_key, "rb") as f:
                ca_key_pem = f.read()
        else:
            ca_cert_pem, ca_key_pem = generate_ca()
            cls._write_private(ca_key, ca_key_pem)
            with open(ca_crt, "wb") as f:
                f.write(ca_cert_pem)
        cert_pem, key_pem = generate_server_cert(ca_cert_pem, ca_key_pem, hosts)
        sc = cls(directory, ca_cert_pem, cert_pem, key_pem, _tmp=tmp)
        if tmp is not None:
            cls._ephemeral[tuple(sorted(hosts))] = sc
        return sc

    @staticmethod
    def _write_private(path: str, data: bytes) -> None:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(data)

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.server_cert_path, self.server_key_path)
        return ctx


def client_context(ca_pem: bytes | str | None = None,
                   ca_file: str | None = None) -> ssl.SSLContext:
    """A verifying client context trusting the given CA (PEM bytes/str or
    file path)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.check_hostname = True
    if ca_pem is not None:
        if isinstance(ca_pem, bytes):
            ca_pem = ca_pem.decode("ascii")
        ctx.load_verify_locations(cadata=ca_pem)
    elif ca_file is not None:
        ctx.load_verify_locations(cafile=ca_file)
    else:
        ctx.load_default_certs()
    return ctx
