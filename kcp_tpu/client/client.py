"""Clients: cluster-scoped and multi-cluster dynamic access to a store.

The analog of the reference's generated clientsets + dynamic client
(pkg/client/**) plus the fork's multi-cluster routing
(``clientutils.EnableMultiCluster``, reference: pkg/server/server.go:230):
a wildcard client reads/watches across all logical clusters and routes
writes to the logical cluster named in ``metadata.clusterName``.

One dynamic client serves all types — the framework is unstructured
end-to-end, so generated per-type clients would be pure boilerplate. The
same interface is implemented by :class:`kcp_tpu.server.rest.RestClient`
over HTTP, so controllers run equally in-process or remote.
"""

from __future__ import annotations

from ..apis.scheme import GVR, Scheme, default_scheme
from ..store.selectors import LabelSelector
from ..store.store import WILDCARD, LogicalStore, Watch
from ..utils.errors import InvalidError
from ..utils.routing import resolve_write_cluster


def _resource(gvr: GVR | str) -> str:
    return gvr.storage_name if isinstance(gvr, GVR) else gvr


class Client:
    """A view of one logical cluster (or the wildcard) over a LogicalStore."""

    def __init__(self, store: LogicalStore, cluster: str, scheme: Scheme | None = None):
        self._store = store
        self.cluster = cluster
        self.scheme = scheme if scheme is not None else default_scheme()

    def scoped(self, cluster: str) -> "Client":
        return Client(self._store, cluster, self.scheme)

    # -- reads ---------------------------------------------------------

    def get(self, gvr: GVR | str, name: str, namespace: str = "") -> dict:
        return self._store.get(_resource(gvr), self.cluster, name, namespace)

    def list(
        self,
        gvr: GVR | str,
        namespace: str | None = None,
        selector: LabelSelector | None = None,
    ) -> tuple[list[dict], int]:
        return self._store.list(_resource(gvr), self.cluster, namespace, selector)

    def watch(
        self,
        gvr: GVR | str,
        namespace: str | None = None,
        selector: LabelSelector | None = None,
        since_rv: int | None = None,
    ) -> Watch:
        return self._store.watch(_resource(gvr), self.cluster, namespace, selector, since_rv)

    # -- writes --------------------------------------------------------

    def _write_cluster(self, obj: dict) -> str:
        return resolve_write_cluster(self.cluster, obj)

    def create(self, gvr: GVR | str, obj: dict, namespace: str = "") -> dict:
        return self._store.create(_resource(gvr), self._write_cluster(obj), obj, namespace)

    def update(self, gvr: GVR | str, obj: dict, namespace: str = "") -> dict:
        return self._store.update(_resource(gvr), self._write_cluster(obj), obj, namespace)

    def update_status(self, gvr: GVR | str, obj: dict, namespace: str = "") -> dict:
        return self._store.update_status(
            _resource(gvr), self._write_cluster(obj), obj, namespace
        )

    def delete(self, gvr: GVR | str, name: str, namespace: str = "", cluster: str | None = None) -> None:
        target = cluster or self.cluster
        if target == WILDCARD:
            raise InvalidError("wildcard delete requires an explicit cluster")
        self._store.delete(_resource(gvr), target, name, namespace)

    # -- discovery -----------------------------------------------------

    def resources(self) -> list[str]:
        """Served resource names: the scheme's registry (built-ins +
        registered CRDs) plus anything already present in the store."""
        served = {i.gvr.storage_name for i in self.scheme.all()}
        served.update(self._store.resources())
        return sorted(served)

    def openapi_v2(self) -> dict | None:
        """The cluster's ``/openapi/v2`` swagger document (reference:
        the discovery client's OpenAPISchema fetch,
        pkg/crdpuller/discovery.go:60-66). Same resolution as the REST
        handler — attached document, else synthesized from the
        cluster's CRDs — so a puller sees identical schemas over either
        transport."""
        if self._store.openapi_doc is not None:
            return self._store.openapi_doc
        from ..apis import crd as crdapi
        from ..crdpuller.openapi import doc_from_crds

        try:
            crds, _ = self._store.list(crdapi.CRDS.storage_name, self.cluster)
        except Exception:  # noqa: BLE001 — no CRDs ⇒ empty document
            crds = []
        return doc_from_crds(crds) if crds else None


class MultiClusterClient(Client):
    """Wildcard client — list/watch across all tenants, routed writes.

    The fork's EnableMultiCluster behavior (SURVEY.md §2.3): reads span
    every logical cluster; each written object carries its destination in
    ``metadata.clusterName``.
    """

    def __init__(self, store: LogicalStore, scheme: Scheme | None = None):
        # accepts the SERVER's scheme so in-process controllers (CRD
        # lifecycle, negotiation) register dynamic resources into the
        # same registry the REST handler serves from — without it, a CRD
        # created over REST never becomes servable over REST
        super().__init__(store, WILDCARD, scheme)

    def cluster_client(self, cluster: str) -> Client:
        # share the scheme: CRD registrations must be visible to every view
        return Client(self._store, cluster, self.scheme)
