from .client import Client, MultiClusterClient
from .informer import Informer, SharedInformerFactory

__all__ = ["Client", "MultiClusterClient", "Informer", "SharedInformerFactory",
           "SmartRestClient", "SmartMultiClusterRestClient", "rest_client",
           "multicluster_rest_client", "smart_enabled"]

_SMART = {"SmartRestClient", "SmartMultiClusterRestClient", "rest_client",
          "multicluster_rest_client", "smart_enabled"}


def __getattr__(name: str):
    # lazy: kcp_tpu.client.smart pulls in the server package (RestClient,
    # pools); importing it eagerly here would make `import kcp_tpu.client`
    # load the whole serving stack
    if name in _SMART:
        from . import smart

        return getattr(smart, name)
    raise AttributeError(name)
