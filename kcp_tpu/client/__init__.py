from .client import Client, MultiClusterClient
from .informer import Informer, SharedInformerFactory

__all__ = ["Client", "MultiClusterClient", "Informer", "SharedInformerFactory"]
