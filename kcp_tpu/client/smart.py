"""Smart clients: shard-aware direct routing over the rendezvous ring.

The PR 6 ring is deliberately coordination-free — any client can compute
a cluster's owning shard from the shard list alone (the reference's
``clientutils.EnableMultiCluster`` write routing, SURVEY.md §2.3, done
client-side). ``KCP_SMART_CLIENT=1`` turns that into deleted hops: a
:class:`SmartRestClient` fetches the router's ``GET /ring`` once
(``{epoch, shards[]}``), computes the HRW owner locally
(:mod:`kcp_tpu.sharding.ring`), holds per-shard pooled connections
(:class:`~kcp_tpu.store.remote.ConnectionPool`), and sends
single-cluster verbs and watches **direct** to the owning shard —
wildcard and non-resource requests still go via the router.

Correctness never depends on ring freshness:

- every direct request carries ``X-Kcp-Ring-Epoch`` (the epoch the
  client's ring came from); a shard that knows the ring and does NOT
  own the target cluster answers a typed 410 carrying its own epoch;
- any 410 / 503 / connect-refused / breaker-open answer on the direct
  path triggers a (rate-limited) re-fetch of ``/ring`` **and a one-shot
  fallback through the router** — the router always routes over ITS
  current ring, so the request lands even mid-ring-change, and the next
  request goes direct over the refreshed ring;
- a base URL that serves no ``/ring`` (a monolith, a bare shard) parks
  smart mode: the client behaves exactly like a plain
  :class:`~kcp_tpu.server.rest.RestClient`.

The ring document also carries the router's pending-migration
``overrides`` (cluster -> shard name): while a cluster's WAL is moving
to a new owner, the override pins it to its OLD shard, so smart clients
keep landing direct hits mid-migration and flip atomically with the
fleet the moment the router drops the pin. ``KCP_RING_REFRESH_S=N``
(default off) adds a background periodic re-fetch through the same
epoch-verified path — useful on fleets that scale out while a client
sits idle (no traffic means no 410 to trigger the reactive refresh).

Responses on the direct path are byte-identical to routed responses
(modulo hop-specific headers) — the differential fuzz in
tests/test_smartclient.py and the sha256 cross-check in
``bench.py --smartclient`` hold that line.
"""

from __future__ import annotations

import http.client
import os
import threading
import time
from urllib.parse import unquote, urlsplit

from ..analysis.sanitize import make_lock
from ..server.rest import MultiClusterRestClient, RestClient, RestWatch
from ..store.store import WILDCARD
from ..utils import errors
from ..utils.trace import REGISTRY

#: the ring-freshness handshake header: requests carry the client's ring
#: epoch; ring-mismatch 410s carry the shard's
RING_EPOCH_HEADER = "X-Kcp-Ring-Epoch"

_DIRECT = REGISTRY.counter(
    "smart_client_direct_total",
    "requests/watches a smart client served direct-to-shard (no router "
    "hop)")
_FALLBACK = REGISTRY.counter(
    "smart_client_fallback_total",
    "direct attempts that fell back through the router (connect "
    "refused, breaker open, 410 ring mismatch, 503) — each one also "
    "triggers a ring re-fetch")
_REFRESH = REGISTRY.counter(
    "smart_client_ring_refreshes_total",
    "successful GET /ring fetches (initial + staleness-triggered)")

#: direct-path triggers that mean "the ring may be stale / the shard is
#: not servable": refresh the ring and take the router hop this once.
#: 504 is a follower's RV-barrier timeout (FrontierWaitTimeout): the
#: router hop reaches the primary, which IS the frontier.
_FALLBACK_STATUSES = (410, 503, 504)


def smart_enabled() -> bool:
    """``KCP_SMART_CLIENT=1``: construction sites that honor the env
    gate (scenario workloads, benches) build smart clients."""
    return os.environ.get("KCP_SMART_CLIENT", "0").lower() in (
        "1", "true", "on")


def ring_refresh_interval() -> float:
    """``KCP_RING_REFRESH_S``: background periodic ring re-fetch cadence
    in seconds; 0 (the default) disables the refresher — the reactive
    410/503-triggered refresh is the only freshness mechanism then."""
    try:
        return max(0.0, float(os.environ.get("KCP_RING_REFRESH_S", "0")
                              or 0.0))
    except ValueError:
        return 0.0


class _RingState:
    """Ring + per-shard pools, SHARED across every ``scoped()`` clone
    of one smart client (like the discovery cache and breaker)."""

    def __init__(self, pool_cap: int | None):
        self.lock = make_lock("smart.ring")
        self.ring = None            # ShardRing | None
        self.epoch = 0
        self.pools: dict[str, object] = {}   # shard url -> ConnectionPool
        self.last_fetch = -1e9      # rate limit on /ring fetches
        self.parked_until = 0.0     # /ring unavailable: plain-client mode
        self.cap = pool_cap if pool_cap is not None else int(
            os.environ.get("KCP_ROUTER_POOL", "8"))
        self.stop = threading.Event()   # ends the background refresher


class SmartRestClient(RestClient):
    """A RestClient that goes direct to the owning shard when it can.

    Drop-in: same constructor and verb surface as RestClient against
    the ROUTER's base URL. ``scoped()`` clones share the ring state,
    the per-shard pools, and all the fallback bookkeeping.
    """

    def __init__(self, base_url: str, cluster: str = "admin",
                 scheme=None, token: str = "",
                 ca_data: bytes | str | None = None,
                 ca_file: str | None = None,
                 pool_cap: int | None = None):
        super().__init__(base_url, cluster, scheme, token=token,
                         ca_data=ca_data, ca_file=ca_file)
        self._ring_state = _RingState(pool_cap)
        interval = ring_refresh_interval()
        if interval > 0:
            # one refresher per client FAMILY (scoped() clones share the
            # ring state, so they share this thread too); it dies with
            # close() or the process (daemon)
            t = threading.Thread(
                target=self._refresh_loop, args=(interval,),
                name="smart-ring-refresh", daemon=True)
            t.start()

    def _refresh_loop(self, interval: float) -> None:
        st = self._ring_state
        while not st.stop.wait(interval):
            # forced: the cadence itself is the rate limit, and an idle
            # client never generates the 410 that would trigger the
            # reactive path; parked base URLs still short-circuit inside
            self._refresh_ring(force=True)

    # -------------------------------------------------------------- ring

    def _refresh_ring(self, force: bool = False) -> None:
        """Fetch ``GET /ring`` from the router and swap the shared ring
        state (rate-limited; concurrent refreshers coalesce). A base URL
        that refuses /ring parks smart mode for a few seconds."""
        from ..sharding.ring import Shard, ShardRing

        st = self._ring_state
        now = time.monotonic()
        with st.lock:
            # opportunistic refreshes coalesce behind a floor; a FORCED
            # refresh (a staleness signal in hand) always proceeds — its
            # caller is already paying a router hop, so one /ring GET per
            # fallback is proportional overhead, not a storm
            if not force and now < st.last_fetch + 0.25:
                return
            if now < st.parked_until:
                return
            st.last_fetch = now
        try:
            body = RestClient._request(self, "GET", "/ring") or {}
            shards = [Shard(s["name"], s["url"].rstrip("/"),
                            tuple(s.get("replicas", ())))
                      for s in body.get("shards", [])]
            # pending-migration pins ride the ring doc: owner_index()
            # keeps resolving a migrating cluster to its OLD shard until
            # the router drops the pin (the atomic per-cluster flip)
            overrides = {str(c): str(n) for c, n in
                         (body.get("overrides") or {}).items()}
            ring = ShardRing(shards, overrides) if shards else None
        except (errors.ApiError, ConnectionError, OSError, ValueError,
                KeyError, TypeError, http.client.HTTPException):
            ring = None
        if ring is None:
            # no ring here (monolith / bare shard / router mid-restart):
            # park and serve routed — plain-client behavior
            with st.lock:
                st.parked_until = now + 5.0
            return
        epoch = int(body.get("epoch", 0))
        stale: list[object] = []
        with st.lock:
            st.ring = ring
            st.epoch = epoch
            live = {s.url for s in ring.shards}
            for url in [u for u in st.pools if u not in live]:
                stale.append(st.pools.pop(url))
        for pool in stale:
            # closed pools finish in-flight borrows and close on return
            pool.close()
        _REFRESH.inc()

    def _ring_snapshot(self):
        """(ring, epoch) — fetching lazily on first use; (None, 0) when
        the base URL serves no ring."""
        st = self._ring_state
        with st.lock:
            ring, epoch = st.ring, st.epoch
        if ring is None:
            self._refresh_ring()
            with st.lock:
                ring, epoch = st.ring, st.epoch
        return ring, epoch

    def _shard_pool(self, url: str):
        from ..store.remote import ConnectionPool

        st = self._ring_state
        with st.lock:
            pool = st.pools.get(url)
            if pool is None:
                pool = st.pools[url] = ConnectionPool(
                    url, token=self.token, ca_data=self.ca_data,
                    ca_file=self.ca_file, cap=st.cap)
        return pool

    @staticmethod
    def _target_cluster(target: str) -> str | None:
        """The logical cluster a request target is scoped to, or None
        when the request is not direct-eligible (non-resource paths,
        the wildcard)."""
        path = target.partition("?")[0]
        if not path.startswith("/clusters/"):
            return None
        seg = unquote(path[len("/clusters/"):].partition("/")[0])
        if not seg or seg == WILDCARD:
            return None
        return seg

    # ---------------------------------------------------------- plumbing

    def _roundtrip(self, method: str, path: str, payload: bytes | None,
                   headers: dict[str, str]):
        """Route one round trip: direct to the HRW owner for
        single-cluster targets, via the router otherwise — with the
        one-shot router fallback on any ring-staleness signal. Every
        verb (and ``request_raw``) funnels through here, so the whole
        RestClient surface inherits smart routing."""
        cluster = self._target_cluster(path)
        if cluster is None:
            return super()._roundtrip(method, path, payload, headers)
        ring, epoch = self._ring_snapshot()
        if ring is None:
            return super()._roundtrip(method, path, payload, headers)
        shard = ring.shards[ring.owner_index(cluster)]
        pool = self._shard_pool(shard.url)
        h = dict(headers)
        h[RING_EPOCH_HEADER] = str(epoch)
        try:
            with pool.client() as c:
                status, resp, data = c._roundtrip(method, path, payload, h)
        except (errors.UnavailableError, ConnectionError, OSError,
                TimeoutError, http.client.HTTPException):
            # dead/unreachable shard (or its breaker already open): the
            # ring may have moved under us — refresh + one router hop.
            # The caller's own retry discipline is unchanged: a write
            # whose DIRECT send may have reached the shard surfaces as
            # AlreadyExists on the router retry, exactly like the
            # stale-keep-alive retry case (_roundtrip docstring).
            return self._fallback(method, path, payload, headers)
        if status in _FALLBACK_STATUSES:
            # the shard ANSWERED but refused in a way that means "not
            # me / not now": 410 = ring mismatch (the shard's epoch
            # rides the response headers), 503 = fenced/draining/
            # read-only — the router knows who serves this now
            return self._fallback(method, path, payload, headers)
        _DIRECT.inc()
        return status, resp, data

    def _fallback(self, method: str, path: str, payload: bytes | None,
                  headers: dict[str, str]):
        """The one-shot escape hatch: refresh the ring (forced,
        best-effort) and relay this request through the router."""
        self._refresh_ring(force=True)
        _FALLBACK.inc()
        return super()._roundtrip(method, path, payload, headers)

    # -------------------------------------------------------------- watch

    def watch(self, gvr, namespace: str | None = None, selector=None,
              since_rv: int | None = None,
              bookmarks: bool = True,
              initial_events: bool = False) -> RestWatch:
        """Open a watch stream DIRECT to the owning shard when the ring
        allows (carrying the epoch header); routed otherwise. A direct
        stream that dies or 410s lands in the informer's normal
        resume/relist loop — the relist runs through
        :meth:`_roundtrip`, which refreshes the ring and falls back, so
        a moved shard converges without special watch-side plumbing."""
        routed = super().watch(gvr, namespace, selector,
                               since_rv=since_rv, bookmarks=bookmarks,
                               initial_events=initial_events)
        if self.cluster == WILDCARD:
            return routed
        ring, epoch = self._ring_snapshot()
        if ring is None:
            return routed
        shard = ring.shards[ring.owner_index(self.cluster)]
        pool = self._shard_pool(shard.url)
        from ..utils.circuit import CLOSED

        if pool.breaker.state != CLOSED:
            # known-dead shard: don't burn a connect on a stream that
            # cannot establish — ride the router until the ring moves
            _FALLBACK.inc()
            return routed
        parts = urlsplit(shard.url)
        host = parts.hostname or "127.0.0.1"
        port = parts.port or (443 if parts.scheme == "https" else 80)
        _DIRECT.inc()
        return RestWatch(host, port, routed._path, routed.resource,
                         token=self.token, ssl_context=pool.ssl_context,
                         extra_headers={RING_EPOCH_HEADER: str(epoch)},
                         initial_events=initial_events,
                         session=self._session,
                         session_cluster=self.cluster)

    # ---------------------------------------------------------- lifecycle

    def close(self) -> None:
        super().close()
        st = self._ring_state
        st.stop.set()
        with st.lock:
            pools, st.pools = list(st.pools.values()), {}
            st.ring = None
        for pool in pools:
            pool.close()


class SmartMultiClusterRestClient(SmartRestClient):
    """Wildcard smart client: wildcard verbs ride the router (scatter-
    gather belongs there), ``cluster_client()`` scopes go direct."""

    def __init__(self, base_url: str, scheme=None, token: str = "",
                 ca_data: bytes | str | None = None,
                 ca_file: str | None = None,
                 pool_cap: int | None = None):
        super().__init__(base_url, WILDCARD, scheme, token=token,
                         ca_data=ca_data, ca_file=ca_file,
                         pool_cap=pool_cap)

    def cluster_client(self, cluster: str) -> "SmartRestClient":
        return self.scoped(cluster)


def rest_client(base_url: str, cluster: str = "admin", **kw) -> RestClient:
    """Factory honoring the ``KCP_SMART_CLIENT`` env gate: a smart
    client when it is set, a plain RestClient otherwise. The scenario
    workloads and benches construct through this so one env var flips a
    whole fleet of writers."""
    if smart_enabled():
        return SmartRestClient(base_url, cluster, **kw)
    return RestClient(base_url, cluster, **kw)


def multicluster_rest_client(base_url: str, **kw) -> MultiClusterRestClient:
    """Wildcard twin of :func:`rest_client`."""
    if smart_enabled():
        return SmartMultiClusterRestClient(base_url, **kw)
    return MultiClusterRestClient(base_url, **kw)
